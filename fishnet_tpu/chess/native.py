"""ctypes bindings for the native chesscore library.

Builds fishnet_tpu/cc/chesscore.cpp on first use (g++ -O2 -shared); falls
back gracefully (native() returns None) when no compiler is available, in
which case callers use the pure-Python rules library. The planner uses this
for its hot validate-and-replay path (the role shakmaty's compiled code
plays in the reference, src/queue.rs:554-581).
"""
from __future__ import annotations

import ctypes
import subprocess
import threading
from pathlib import Path
from typing import List, Optional, Tuple

_CC_DIR = Path(__file__).resolve().parent.parent / "cc"
_SRC = _CC_DIR / "chesscore.cpp"
_LIB = _CC_DIR / "libchesscore.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
             str(_SRC), "-o", str(_LIB)],
            check=True, capture_output=True, timeout=120,
        )
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def native() -> Optional[ctypes.CDLL]:
    """The loaded library, building it if needed; None when unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not _LIB.exists() or _LIB.stat().st_mtime < _SRC.stat().st_mtime:
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(str(_LIB))
        except OSError:
            return None
        lib.cc_replay_game.restype = ctypes.c_int
        lib.cc_replay_game.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int,
        ]
        lib.cc_perft.restype = ctypes.c_longlong
        lib.cc_perft.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.cc_legal_moves.restype = ctypes.c_int
        lib.cc_legal_moves.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
        _lib = lib
        return _lib


class NativeError(ValueError):
    pass


def replay_game(fen: str, moves: List[str]) -> Optional[Tuple[str, List[str]]]:
    """Validate and replay with the native core.

    Returns (final_fen, chess960_normalized_moves), None when the native
    library is unavailable, or raises NativeError for invalid input.
    """
    lib = native()
    if lib is None:
        return None
    out_fen = ctypes.create_string_buffer(128)
    out_moves = ctypes.create_string_buffer(16 + 6 * max(len(moves), 1))
    rc = lib.cc_replay_game(
        fen.encode(), " ".join(moves).encode(),
        out_fen, len(out_fen), out_moves, len(out_moves),
    )
    if rc < 0:
        raise NativeError(f"invalid fen ({rc}): {fen!r}")
    if rc > 0:
        raise NativeError(f"illegal uci move {moves[rc - 1]!r} at index {rc - 1}")
    norm = out_moves.value.decode()
    return out_fen.value.decode(), norm.split() if norm else []


def perft(fen: str, depth: int) -> Optional[int]:
    lib = native()
    if lib is None:
        return None
    result = lib.cc_perft(fen.encode(), depth)
    return None if result < 0 else int(result)


def legal_moves(fen: str) -> Optional[List[str]]:
    lib = native()
    if lib is None:
        return None
    buf = ctypes.create_string_buffer(8192)
    rc = lib.cc_legal_moves(fen.encode(), buf, len(buf))
    if rc < 0:
        raise NativeError(f"invalid fen ({rc}): {fen!r}")
    s = buf.value.decode()
    return s.split() if s else []
