"""Lichess variant rules.

The reference client analyses these variants by delegating to Fairy-Stockfish
(reference: src/logger.rs:201-213 short names; src/queue.rs:562-568 routes all
variant jobs to the MultiVariant engine). Here the rules live host-side for
input validation and move replay, and drive the variant-id tensor used by the
device movegen.
"""
from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

from .attacks import KING_ATTACKS
from .position import (
    BACK_RANKS,
    PROMO_RANKS,
    RANK_1,
    RANK_2,
    RANK_7,
    RANK_8,
    InvalidFenError,
    Position,
)
from .types import (
    BLACK,
    FULL_BB,
    KING,
    KNIGHT,
    BISHOP,
    PAWN,
    QUEEN,
    ROOK,
    WHITE,
    Move,
    bb,
    lsb,
    popcount,
    scan,
    square_rank,
)


class ThreeCheckPosition(Position):
    variant = "threeCheck"

    def __init__(self) -> None:
        super().__init__()
        self.checks_given = [0, 0]

    @classmethod
    def starting_fen(cls) -> str:
        return "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 3+3 0 1"

    def _parse_checks_field(self, field: str) -> None:
        # "3+3" = remaining checks; "+0+0" = checks already given
        if field.startswith("+"):
            parts = field[1:].split("+")
            if len(parts) != 2:
                raise InvalidFenError(f"bad check field {field!r}")
            self.checks_given = [int(parts[0]), int(parts[1])]
        else:
            parts = field.split("+")
            if len(parts) != 2:
                raise InvalidFenError(f"bad check field {field!r}")
            self.checks_given = [3 - int(parts[0]), 3 - int(parts[1])]

    def _fen_extra(self) -> Optional[str]:
        cg = self.checks_given or [0, 0]
        return f"{3 - cg[WHITE]}+{3 - cg[BLACK]}"

    def _post_turn_hook(self, prev_turn: int) -> None:
        if self.is_check():
            self.checks_given[prev_turn] += 1

    def _variant_outcome(self) -> Optional[Tuple[Optional[int], str]]:
        for color in (WHITE, BLACK):
            if self.checks_given[color] >= 3:
                return (color, "three checks")
        return None


class KingOfTheHillPosition(Position):
    variant = "kingOfTheHill"

    CENTER = bb(27) | bb(28) | bb(35) | bb(36)  # d4 e4 d5 e5

    def _variant_outcome(self) -> Optional[Tuple[Optional[int], str]]:
        for color in (WHITE, BLACK):
            if self.bbs[color][KING] & self.CENTER:
                return (color, "king in the center")
        return None


class RacingKingsPosition(Position):
    variant = "racingKings"
    has_castling = False

    @classmethod
    def starting_fen(cls) -> str:
        return "8/8/8/8/8/8/krbnNBRK/qrbnNBRQ w - - 0 1"

    def _validate(self) -> None:
        for color in (WHITE, BLACK):
            if popcount(self.bbs[color][KING]) != 1:
                raise InvalidFenError("each side needs exactly one king")
        if self.is_check():
            raise InvalidFenError("racingKings positions can never have a check")

    def legal_moves(self) -> List[Move]:
        moves = []
        for move in self.generate_pseudo_legal():
            if not self._move_is_safe(move):
                continue
            # giving check is illegal in racing kings
            child = self.push(move)
            if child.is_check():
                continue
            moves.append(move)
        return moves

    def is_insufficient_material(self) -> bool:
        return False  # the goal is the race, not mate

    def _variant_outcome(self) -> Optional[Tuple[Optional[int], str]]:
        white_in = bool(self.bbs[WHITE][KING] & RANK_8)
        black_in = bool(self.bbs[BLACK][KING] & RANK_8)
        if white_in and black_in:
            return (None, "both kings in the goal")
        if black_in:
            return (BLACK, "king in the goal")
        if white_in:
            # black gets one rejoinder move to equalize
            if self.turn == BLACK:
                bksq = self.king_sq(BLACK)
                if bksq is not None and any(
                    square_rank(m.to_sq) == 7 and m.from_sq == bksq
                    for m in self.legal_moves()
                ):
                    return None
            return (WHITE, "king in the goal")
        return None


class HordePosition(Position):
    variant = "horde"

    @classmethod
    def starting_fen(cls) -> str:
        return (
            "rnbqkbnr/pppppppp/8/1PP2PP1/PPPPPPPP/PPPPPPPP/PPPPPPPP/PPPPPPPP"
            " w kq - 0 1"
        )

    def _validate(self) -> None:
        if popcount(self.bbs[BLACK][KING]) != 1:
            raise InvalidFenError("black must have exactly one king")
        if self.bbs[WHITE][KING]:
            raise InvalidFenError("the horde has no king")
        if self.bbs[WHITE][PAWN] & RANK_8 or self.bbs[BLACK][PAWN] & RANK_1:
            raise InvalidFenError("pawn on promotion rank")
        if self.turn == WHITE:
            bksq = self.king_sq(BLACK)
            if bksq is not None and self.attackers(WHITE, bksq):
                raise InvalidFenError("side not to move is in check")

    def _double_push_sources(self, us: int) -> int:
        # horde: white pawns on rank 1 may also double-push
        if us == WHITE:
            return RANK_1 | RANK_2
        return RANK_7

    def _double_sets_ep(self, frm: int, us: int) -> bool:
        # a double push from the back rank cannot be captured en passant
        return not (us == WHITE and square_rank(frm) == 0)

    def _variant_outcome(self) -> Optional[Tuple[Optional[int], str]]:
        if not self.occ[WHITE]:
            return (BLACK, "horde destroyed")
        return None

    def is_insufficient_material(self) -> bool:
        return False


class AtomicPosition(Position):
    variant = "atomic"

    def _explosion_zone(self, sq: int) -> int:
        return KING_ATTACKS[sq] | bb(sq)

    def _kings_adjacent(self) -> bool:
        wk, bk = self.king_sq(WHITE), self.king_sq(BLACK)
        return wk is not None and bk is not None and bool(KING_ATTACKS[wk] & bb(bk))

    def checkers(self) -> int:
        if self._kings_adjacent():
            return 0  # adjacent kings can never be in check (capture explodes both)
        return super().checkers()

    def is_check(self) -> bool:
        return bool(self.checkers())

    def _post_move_hook(self, move: Move, us: int, ptype: int, captured) -> None:
        if captured is None:
            return
        # explosion centers on the landing square: the capturer and every
        # non-pawn piece within one king-step are removed (the directly
        # captured piece is already gone)
        self._remove_piece(move.to_sq)
        zone = self._explosion_zone(move.to_sq)
        for color in (WHITE, BLACK):
            for pt in (KNIGHT, BISHOP, ROOK, QUEEN, KING):
                for s in scan(self.bbs[color][pt] & zone):
                    self._remove_piece(s)
                    self.castling &= ~bb(s)

    def generate_pseudo_legal(self) -> Iterator[Move]:
        them_occ = self.occ[self.turn ^ 1]
        for move in super().generate_pseudo_legal():
            # kings never capture in atomic (the capture would explode them)
            pc = self.piece_at(move.from_sq)
            if pc is not None and pc[1] == KING and bb(move.to_sq) & them_occ:
                continue
            yield move

    def _move_is_safe(self, move: Move) -> bool:
        child = self.copy()
        child._apply(move)
        us = self.turn
        if child.king_sq(us ^ 1) is None:
            return True  # exploding the enemy king wins regardless
        if child.king_sq(us) is None:
            return False  # exploding our own king is illegal
        ksq = child.king_sq(us)
        if child._kings_adjacent():
            return True
        return not child.attackers(child.turn, ksq)

    def _variant_outcome(self) -> Optional[Tuple[Optional[int], str]]:
        for color in (WHITE, BLACK):
            if not self.bbs[color][KING]:
                return (color ^ 1, "king exploded")
        return None

    def _validate(self) -> None:
        for color in (WHITE, BLACK):
            if popcount(self.bbs[color][KING]) > 1:
                raise InvalidFenError("too many kings")
        if self.bbs[WHITE][PAWN] & (RANK_1 | RANK_8) or self.bbs[BLACK][PAWN] & (RANK_1 | RANK_8):
            raise InvalidFenError("pawn on back rank")
        them = self.turn ^ 1
        their_king = self.bbs[them][KING]
        if their_king and not self._kings_adjacent() and self.attackers(self.turn, lsb(their_king)):
            raise InvalidFenError("side not to move is in check")


class AntichessPosition(Position):
    variant = "antichess"
    has_castling = False

    def _promotion_pieces(self) -> Tuple[int, ...]:
        return (QUEEN, ROOK, BISHOP, KNIGHT, KING)

    def _validate(self) -> None:
        if self.bbs[WHITE][PAWN] & (RANK_1 | RANK_8) or self.bbs[BLACK][PAWN] & (RANK_1 | RANK_8):
            raise InvalidFenError("pawn on back rank")

    def legal_moves(self) -> List[Move]:
        moves = list(self.generate_pseudo_legal())
        them_occ = self.occ[self.turn ^ 1]
        captures = [
            m for m in moves
            if bb(m.to_sq) & them_occ
            or (m.drop is None and self.piece_at(m.from_sq)[1] == PAWN
                and self.ep_square is not None and m.to_sq == self.ep_square)
        ]
        return captures if captures else moves

    def _move_is_safe(self, move: Move) -> bool:
        return True  # no check concept

    def _variant_outcome(self) -> Optional[Tuple[Optional[int], str]]:
        if not self.occ[self.turn]:
            return (self.turn, "all pieces lost")
        if not self.legal_moves():
            return (self.turn, "stalemate")  # stalemated side wins
        return None

    def outcome(self, legal_moves=None):
        if not self.occ[self.turn]:
            return (self.turn, "all pieces lost")
        if legal_moves is None:
            legal_moves = self.legal_moves()
        if not legal_moves:
            return (self.turn, "stalemate")  # stalemated side wins
        if self.halfmove >= 100:
            return (None, "50-move rule")
        return None


class CrazyhousePosition(Position):
    variant = "crazyhouse"

    def __init__(self) -> None:
        super().__init__()
        self.pockets = [[0] * 5, [0] * 5]

    @classmethod
    def starting_fen(cls) -> str:
        return "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR[] w KQkq - 0 1"

    @classmethod
    def from_fen(cls, fen: str) -> "CrazyhousePosition":
        pos = super().from_fen(fen)
        if pos.pockets is None:
            pos.pockets = [[0] * 5, [0] * 5]
        return pos

    def _on_capture(self, us: int, cap_pc, cap_sq: int, cap_was_promoted: bool) -> None:
        ptype = PAWN if cap_was_promoted else cap_pc[1]
        self.pockets[us][ptype] += 1

    def _drop_moves(self, us: int) -> Iterator[Move]:
        if self.pockets is None:
            return
        empty = ~self.occ_all & FULL_BB
        for ptype in range(5):
            if self.pockets[us][ptype] <= 0:
                continue
            targets = empty
            if ptype == PAWN:
                targets &= ~(RANK_1 | RANK_8)
            for to in scan(targets):
                yield Move(0, to, drop=ptype)

    def is_insufficient_material(self) -> bool:
        return False  # material comes back from the pocket


VARIANTS = {
    "standard": Position,
    "chess960": Position,
    "fromPosition": Position,
    "threeCheck": ThreeCheckPosition,
    "3check": ThreeCheckPosition,
    "kingOfTheHill": KingOfTheHillPosition,
    "racingKings": RacingKingsPosition,
    "horde": HordePosition,
    "atomic": AtomicPosition,
    "antichess": AntichessPosition,
    "crazyhouse": CrazyhousePosition,
}


def position_class(variant: str):
    try:
        return VARIANTS[variant]
    except KeyError:
        raise ValueError(f"unsupported variant: {variant!r}") from None


def from_fen(fen: str, variant: str = "standard") -> Position:
    return position_class(variant).from_fen(fen)
