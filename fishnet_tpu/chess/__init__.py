"""Host-side chess rules library (shakmaty's role in the reference client)."""
from .types import (
    BLACK,
    BISHOP,
    KING,
    KNIGHT,
    PAWN,
    QUEEN,
    ROOK,
    WHITE,
    Move,
    parse_square,
    square,
    square_file,
    square_name,
    square_rank,
)
from .position import (
    Chess960Position,
    IllegalMoveError,
    InvalidFenError,
    Position,
    STARTING_FEN,
)
from .perft import perft, perft_divide

__all__ = [
    "BLACK", "BISHOP", "KING", "KNIGHT", "PAWN", "QUEEN", "ROOK", "WHITE",
    "Move", "parse_square", "square", "square_file", "square_name", "square_rank",
    "Chess960Position", "IllegalMoveError", "InvalidFenError", "Position",
    "STARTING_FEN", "perft", "perft_divide",
]
