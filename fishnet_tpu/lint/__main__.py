"""CLI for fishnet-lint.

    python -m fishnet_tpu.lint                    # lint the repo
    python -m fishnet_tpu.lint --format=github    # CI annotations
    python -m fishnet_tpu.lint --write-baseline   # absolve current findings
    python -m fishnet_tpu.lint --list-rules

Exit codes: 0 clean (or everything baselined), 1 active findings or a
stale baseline, 2 internal error (unparseable file, bad baseline).
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional

from .core import Project, dump_baseline, families, load_baseline, run_lint

DEFAULT_BASELINE = "lint-baseline.json"


def _detect_root() -> Path:
    import fishnet_tpu

    return Path(fishnet_tpu.__file__).resolve().parents[1]


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m fishnet_tpu.lint",
        description="Project-invariant static analysis for fishnet-tpu.",
    )
    parser.add_argument(
        "--root", type=Path, default=None,
        help="project root to scan (default: the repo this package is in)",
    )
    parser.add_argument(
        "--format", choices=("text", "github", "json"), default="text",
        help="output format (github emits workflow error annotations)",
    )
    parser.add_argument(
        "--baseline", type=Path, default=None,
        help=f"baseline file (default: <root>/{DEFAULT_BASELINE} when it "
             "exists)",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="write the current active findings to the baseline and exit 0",
    )
    parser.add_argument(
        "--family", action="append", dest="only_families", metavar="NAME",
        help="run only this rule family (repeatable); see --list-rules",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="list rule families and exit",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        # importing run_lint's rule modules registers the families
        from . import aot_rules  # noqa: F401
        from . import cache_rules  # noqa: F401
        from . import concurrency_rules  # noqa: F401
        from . import config_rules  # noqa: F401
        from . import obs_rules  # noqa: F401
        from . import trace_rules  # noqa: F401
        from . import wire_rules  # noqa: F401

        for name, fn in families():
            doc = (fn.__doc__ or "").strip().splitlines()
            print(f"{name}: {doc[0] if doc else ''}".strip())
        return 0

    root = (args.root or _detect_root()).resolve()
    baseline_path = args.baseline or (root / DEFAULT_BASELINE)

    try:
        project = Project.load(root)
    except SyntaxError as e:
        print(f"fishnet-lint: {e}", file=sys.stderr)
        return 2

    baseline: List[str] = []
    if not args.write_baseline and baseline_path.is_file():
        try:
            baseline = load_baseline(baseline_path)
        except (ValueError, json.JSONDecodeError) as e:
            print(f"fishnet-lint: {e}", file=sys.stderr)
            return 2

    only = set(args.only_families) if args.only_families else None
    result = run_lint(project, baseline=baseline, only_families=only)

    if args.write_baseline:
        baseline_path.write_text(dump_baseline(result.active),
                                 encoding="utf-8")
        print(f"fishnet-lint: wrote {len(result.active)} entries to "
              f"{baseline_path}")
        return 0

    if args.format == "json":
        print(json.dumps({
            "findings": [vars(f) for f in result.findings],
            "stale_baseline": result.stale_baseline,
        }, indent=2))
    else:
        for f in result.findings:
            print(f.format_github() if args.format == "github"
                  else f.format_text())
        for entry in result.stale_baseline:
            print(f"stale baseline entry (finding fixed? run "
                  f"--write-baseline): {entry}")
        active = len(result.active)
        baselined = len(result.findings) - active
        tail = f", {baselined} baselined" if baselined else ""
        stale = len(result.stale_baseline)
        tail += f", {stale} stale baseline entries" if stale else ""
        print(f"fishnet-lint: {active} active findings{tail}")

    return 1 if (result.failed or result.stale_baseline) else 0


if __name__ == "__main__":
    sys.exit(main())
