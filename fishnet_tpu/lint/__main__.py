"""CLI for fishnet-lint.

    python -m fishnet_tpu.lint                    # lint the repo
    python -m fishnet_tpu.lint --format=github    # CI annotations
    python -m fishnet_tpu.lint --write-baseline   # absolve current findings
    python -m fishnet_tpu.lint --changed          # findings in dirty files
    python -m fishnet_tpu.lint --changed origin/main   # ...vs a base ref
    python -m fishnet_tpu.lint --explain trace-sync    # docs for one rule
    python -m fishnet_tpu.lint --list-rules

Exit codes: 0 clean (or everything baselined), 1 active findings or a
stale baseline, 2 internal error (unparseable file, bad baseline).
"""
from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path
from typing import List, Optional, Set

from .core import Project, dump_baseline, families, load_baseline, run_lint

DEFAULT_BASELINE = "lint-baseline.json"


def _detect_root() -> Path:
    import fishnet_tpu

    return Path(fishnet_tpu.__file__).resolve().parents[1]


def _changed_files(root: Path, base: str) -> Set[str]:
    """Root-relative paths of files changed vs `base`, plus untracked
    files — the pre-push view of 'what did I touch'."""
    out: Set[str] = set()
    for cmd in (
        ["git", "diff", "--name-only", base, "--"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        proc = subprocess.run(
            cmd, cwd=root, capture_output=True, text=True, timeout=30,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"{' '.join(cmd)}: {proc.stderr.strip() or 'failed'}"
            )
        out.update(line.strip() for line in proc.stdout.splitlines()
                   if line.strip())
    return out


def _explain(root: Path, rule: str) -> int:
    """Print the docs/lint.md entry for a rule (table row) or a whole
    rule-family section; the docs are the single source of rule prose,
    so this never drifts from them."""
    doc = root / "docs" / "lint.md"
    if not doc.is_file():
        print(f"fishnet-lint: {doc} not found", file=sys.stderr)
        return 2
    lines = doc.read_text(encoding="utf-8").splitlines()
    # family section: print everything from its `### \`name\`` heading
    # to the next heading
    sect_start = None
    for i, line in enumerate(lines):
        if line.startswith("### ") and f"`{rule}`" in line.split("—")[0]:
            sect_start = i
            break
    if sect_start is not None:
        for line in lines[sect_start + 1:]:
            if line.startswith(("## ", "### ")):
                break
            print(line)
        return 0
    # single rule: its table row, plus the owning section heading
    heading = ""
    for line in lines:
        if line.startswith("### "):
            heading = line[4:].strip()
        if line.startswith(f"| `{rule}` |"):
            cells = [c.strip() for c in line.strip("|").split("|")]
            print(f"{rule} (family: {heading})")
            print()
            print(f"Fires on: {cells[1] if len(cells) > 1 else ''}")
            print()
            print(f"Suppress inline with `# fishnet-lint: disable={rule}` "
                  f"(same line or the comment line above); full docs in "
                  f"docs/lint.md.")
            return 0
    print(f"fishnet-lint: no docs entry for rule {rule!r} — see "
          f"--list-rules for families and docs/lint.md for rules",
          file=sys.stderr)
    return 2


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m fishnet_tpu.lint",
        description="Project-invariant static analysis for fishnet-tpu.",
    )
    parser.add_argument(
        "--root", type=Path, default=None,
        help="project root to scan (default: the repo this package is in)",
    )
    parser.add_argument(
        "--format", choices=("text", "github", "json"), default="text",
        help="output format (github emits workflow error annotations)",
    )
    parser.add_argument(
        "--baseline", type=Path, default=None,
        help=f"baseline file (default: <root>/{DEFAULT_BASELINE} when it "
             "exists)",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="write the current active findings to the baseline and exit 0",
    )
    parser.add_argument(
        "--family", action="append", dest="only_families", metavar="NAME",
        help="run only this rule family (repeatable); see --list-rules",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="list rule families and exit",
    )
    parser.add_argument(
        "--changed", nargs="?", const="HEAD", default=None, metavar="BASE",
        help="only report findings in files changed vs BASE (default "
             "HEAD: working-tree changes plus untracked files); the whole "
             "project is still parsed so cross-file rules see full context",
    )
    parser.add_argument(
        "--explain", metavar="RULE", default=None,
        help="print the docs/lint.md entry for a rule or rule family "
             "and exit",
    )
    args = parser.parse_args(argv)

    if args.explain:
        return _explain((args.root or _detect_root()).resolve(),
                        args.explain)

    if args.list_rules:
        # importing run_lint's rule modules registers the families
        from . import aot_rules  # noqa: F401
        from . import cache_rules  # noqa: F401
        from . import concurrency_rules  # noqa: F401
        from . import config_rules  # noqa: F401
        from . import dataflow_rules  # noqa: F401
        from . import mesh_rules  # noqa: F401
        from . import obs_rules  # noqa: F401
        from . import trace_rules  # noqa: F401
        from . import wire_rules  # noqa: F401

        for name, fn in families():
            doc = (fn.__doc__ or "").strip().splitlines()
            print(f"{name}: {doc[0] if doc else ''}".strip())
        return 0

    root = (args.root or _detect_root()).resolve()
    baseline_path = args.baseline or (root / DEFAULT_BASELINE)

    try:
        project = Project.load(root)
    except SyntaxError as e:
        print(f"fishnet-lint: {e}", file=sys.stderr)
        return 2

    baseline: List[str] = []
    if not args.write_baseline and baseline_path.is_file():
        try:
            baseline = load_baseline(baseline_path)
        except (ValueError, json.JSONDecodeError) as e:
            print(f"fishnet-lint: {e}", file=sys.stderr)
            return 2

    only = set(args.only_families) if args.only_families else None
    result = run_lint(project, baseline=baseline, only_families=only)

    if args.changed is not None:
        # scope the REPORT, not the analysis: cross-file rules (config
        # registry, wire pairs) already saw the whole project above. A
        # diff-scoped run also can't judge baseline staleness, so stale
        # entries neither print nor fail here.
        try:
            changed = _changed_files(root, args.changed)
        except (RuntimeError, OSError, subprocess.SubprocessError) as e:
            print(f"fishnet-lint: --changed: {e}", file=sys.stderr)
            return 2
        result.findings = [f for f in result.findings if f.path in changed]
        result.stale_baseline = []

    if args.write_baseline:
        baseline_path.write_text(dump_baseline(result.active),
                                 encoding="utf-8")
        print(f"fishnet-lint: wrote {len(result.active)} entries to "
              f"{baseline_path}")
        return 0

    if args.format == "json":
        print(json.dumps({
            "findings": [vars(f) for f in result.findings],
            "stale_baseline": result.stale_baseline,
        }, indent=2))
    else:
        for f in result.findings:
            print(f.format_github() if args.format == "github"
                  else f.format_text())
        for entry in result.stale_baseline:
            print(f"stale baseline entry (finding fixed? run "
                  f"--write-baseline): {entry}")
        active = len(result.active)
        baselined = len(result.findings) - active
        tail = f", {baselined} baselined" if baselined else ""
        stale = len(result.stale_baseline)
        tail += f", {stale} stale baseline entries" if stale else ""
        print(f"fishnet-lint: {active} active findings{tail}")

    return 1 if (result.failed or result.stale_baseline) else 0


if __name__ == "__main__":
    sys.exit(main())
