"""AOT asset-store discipline rules.

Serialized executables are only loadable under the exact store
fingerprint + program key they were exported with (aot/keys.py); a
serialize/deserialize call made anywhere else produces artifacts with
no compat envelope — they load under skewed jax versions, stale knob
values, or the wrong device kind and fail (or worse, silently answer)
at runtime. All export/import of compiled programs must route through
the registry (fishnet_tpu/aot/registry.py), which keys every artifact.

Rules:
  aot-unkeyed-export   any call that resolves to
                       jax.experimental.serialize_executable.serialize /
                       deserialize_and_load, or jax.export.* — in any
                       package/tool file other than
                       fishnet_tpu/aot/registry.py.
"""
from __future__ import annotations

import ast
from typing import List, Set

from .core import Finding, Project, SourceFile, dotted, register_family

# the one file allowed to touch the serialization APIs directly
_ALLOWED = "fishnet_tpu/aot/registry.py"

_SER_MODULE = "jax.experimental.serialize_executable"
_SER_FUNCS = {"serialize", "deserialize_and_load"}


def _export_call_sites(src: SourceFile) -> List[ast.Call]:
    """Every call in this file that resolves to an executable
    serialization API: serialize/deserialize_and_load through any
    import form of jax.experimental.serialize_executable, and anything
    under jax.export (an alias of it included)."""
    ser_mod_aliases: Set[str] = set()   # alias -> serialize_executable mod
    export_mod_aliases: Set[str] = set()  # alias -> jax.export mod
    bare_names: Set[str] = set()        # from-imported serialize funcs
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == _SER_MODULE:
                    ser_mod_aliases.add(alias.asname or alias.name)
                elif alias.name == "jax.export":
                    export_mod_aliases.add(alias.asname or alias.name)
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                continue
            if node.module == _SER_MODULE:
                for alias in node.names:
                    if alias.name in _SER_FUNCS:
                        bare_names.add(alias.asname or alias.name)
            elif node.module == "jax.experimental":
                for alias in node.names:
                    if alias.name == "serialize_executable":
                        ser_mod_aliases.add(alias.asname or alias.name)
            elif node.module == "jax":
                for alias in node.names:
                    if alias.name == "export":
                        export_mod_aliases.add(alias.asname or alias.name)
            elif node.module == "jax.export":
                for alias in node.names:
                    bare_names.add(alias.asname or alias.name)

    sites: List[ast.Call] = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        if not name:
            continue
        head, _, tail = name.rpartition(".")
        if name in bare_names:
            sites.append(node)
        elif head in ser_mod_aliases and tail in _SER_FUNCS:
            sites.append(node)
        elif any(head == m or head.startswith(m + ".")
                 for m in export_mod_aliases):
            sites.append(node)
        elif name.startswith("jax.export."):
            sites.append(node)
    return sites


@register_family("aot")
def check_aot_keyed_export(project: Project) -> List[Finding]:
    """Executable serialization stays behind the fingerprint key."""
    findings: List[Finding] = []
    for src in project.in_dirs("fishnet_tpu", "tools", "bench.py"):
        if src.rel == _ALLOWED:
            continue
        for node in _export_call_sites(src):
            findings.append(src.finding(
                "aot-unkeyed-export", node,
                "executable serialization outside aot/registry.py "
                "produces artifacts with no store fingerprint or program "
                "key — they outlive jax upgrades and knob flips and fail "
                "(or mis-answer) at deserialize; route through "
                "fishnet_tpu/aot/registry.py, which keys every artifact "
                "via aot/keys.py",
            ))
    return findings
