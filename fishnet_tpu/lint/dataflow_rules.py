"""Per-function dataflow rules: donated-buffer lifetimes and async
shared-state mutation ordering.

Two rules, one engine each:

`jit-donate-use-after` — the PR-5/PR-8 bug class as a lint error. The
segment/merge/init jits donate operands (`donate_argnums` /
`donate_argnames` in ops/search.py and parallel/mesh.py), so the input
handles are dead the moment the call is issued and every caller must
rebind to the outputs. XLA:CPU only *warns* when donation is unusable,
so a use-after-donate passes the CPU test tier silently and corrupts
on the TPU. The rule runs a forward def-use pass over every function:
a name passed in a donated position becomes *dead*; any later read of
it is a finding unless an assignment rebound the name first.

The pass is deliberately may-miss, never may-false-positive, because
the pipelined scheduler loops donate speculatively on one branch and
read the same name only on the mutually-exclusive other branch:

- at an `if` join the dead set is the INTERSECTION of the branches
  (a name donated on only one path is considered live after the join);
- loop bodies get two passes so a donation at the tail of iteration i
  is seen by a read at the head of iteration i+1;
- a bare-name alias (`cur = p_state`) propagates deadness without
  itself counting as a read — the alias copies the handle, it does not
  touch the buffer;
- nested `def`s are analyzed as their own functions (a closure body
  runs at call time, not at definition time).

`conc-await-shared-mutate` — check-then-act races in the asyncio
layer (the PR-12 plan-time admission bug). Inside an `async def` in
serve/, fleet/, or cache/, a read of `obj.attr` followed by an `await`
followed by a write to the same `obj.attr` means the written value was
computed from state another task may have changed during the
suspension. Exempt when both ends sit under one enclosing lock
`with`/`async with`, when the function carries a
`# fishnet-lint: single-writer` annotation (same line as the `async
def` or the line directly above), or when the write is an augmented
assignment (its own read does not straddle anything). Sync helpers are
out of scope — they run under `to_thread`/executors or atomically
between suspension points.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .core import Finding, Project, SourceFile, dotted, register_family

# ------------------------------------------------- jit-donate-use-after

# The known donating entry points (ops/search.py, parallel/mesh.py) and
# their donated positions: {callee-name: (argnums, argnames)}. These
# apply everywhere in scope — the names are unambiguous.
DONATING_CALLS: Dict[str, Tuple[Tuple[int, ...], Tuple[str, ...]]] = {
    "_run_segment_jit": ((1, 2), ()),      # state, tt
    "_merge_lanes_jit": ((0, 1), ()),      # state, fresh
    "_init_state_jit": ((), ("hist_hash", "hist_halfmove")),
    "run_segment_sharded": ((2, 3), ()),   # state, ttab (after mesh, params)
    "refill_lanes_sharded": ((2,), ()),    # state
    "refill_lanes": ((1,), ()),            # state
}

# Local closure wrappers over the donating jits inside the two scheduler
# modules. The names are generic, so they only register there.
WRAPPER_SCOPE = ("fishnet_tpu/engine/tpu.py", "fishnet_tpu/ops/search.py")
WRAPPER_DONATING_CALLS: Dict[str, Tuple[Tuple[int, ...], Tuple[str, ...]]] = {
    "dispatch": ((0, 1), ()),              # st, table
    "flush_adm": ((0,), ()),               # st
    "do_refill": ((0,), ()),               # st
}

# tests/ deliberately poke donated handles (the is_deleted regression
# tests in test_pipeline.py / test_mesh_refill.py assert the read
# RAISES); the package, drivers and bench carry the rebind discipline.
DONATE_SCOPE = ("fishnet_tpu/", "tools/", "bench.py")


def _int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """A literal int or tuple-of-ints, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, ast.Tuple):
        out: List[int] = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, int)):
                return None
            out.append(elt.value)
        return tuple(out)
    return None


def _str_tuple(node: ast.AST) -> Optional[Tuple[str, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, ast.Tuple):
        out: List[str] = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, str)):
                return None
            out.append(elt.value)
        return tuple(out)
    return None


def _module_jit_donations(
    tree: ast.Module,
) -> Dict[str, Tuple[Tuple[int, ...], Tuple[str, ...]]]:
    """Names bound (at any nesting) to an expression containing a
    `jax.jit(..., donate_argnums=...)` call: `_my_jit = jax.jit(fn,
    donate_argnums=(1,))` or `_my_jit = registry.wrap("k", jax.jit(fn,
    donate_argnums=(1, 2)), ...)`."""
    found: Dict[str, Tuple[Tuple[int, ...], Tuple[str, ...]]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        donation = None
        for call in ast.walk(node.value):
            if not isinstance(call, ast.Call):
                continue
            if not dotted(call.func).endswith("jit"):
                continue
            nums: Tuple[int, ...] = ()
            names: Tuple[str, ...] = ()
            for kw in call.keywords:
                if kw.arg == "donate_argnums":
                    nums = _int_tuple(kw.value) or ()
                elif kw.arg == "donate_argnames":
                    names = _str_tuple(kw.value) or ()
            if nums or names:
                donation = (nums, names)
                break
        if donation is None:
            continue
        for target in node.targets:
            if isinstance(target, ast.Name):
                found[target.id] = donation
    return found


class _DeadSet:
    """Names whose device buffers were donated: name -> donating site
    description (for the finding message)."""

    def __init__(self, entries: Optional[Dict[str, str]] = None) -> None:
        self.entries: Dict[str, str] = dict(entries or {})

    def copy(self) -> "_DeadSet":
        return _DeadSet(self.entries)

    @staticmethod
    def intersect(sets: Sequence["_DeadSet"]) -> "_DeadSet":
        if not sets:
            return _DeadSet()
        keys = set(sets[0].entries)
        for s in sets[1:]:
            keys &= set(s.entries)
        return _DeadSet({k: sets[0].entries[k] for k in keys})


class _DonateFlow:
    """Forward flow over one function body."""

    def __init__(
        self,
        src: SourceFile,
        registry: Dict[str, Tuple[Tuple[int, ...], Tuple[str, ...]]],
    ) -> None:
        self.src = src
        self.registry = registry
        # findings dedup across the two loop passes: (line, col, name)
        self.findings: Dict[Tuple[int, int, str], Finding] = {}

    # -- entry point

    def run(self, fn: ast.AST) -> List[Finding]:
        self._block(getattr(fn, "body", []), _DeadSet())
        return [self.findings[k] for k in sorted(self.findings)]

    # -- statement flow

    def _block(self, stmts: Iterable[ast.stmt], dead: _DeadSet) -> _DeadSet:
        for stmt in stmts:
            dead = self._stmt(stmt, dead)
        return dead

    def _stmt(self, stmt: ast.stmt, dead: _DeadSet) -> _DeadSet:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def runs later; its body is its own function.
            # Binding the name kills nothing.
            return dead
        if isinstance(stmt, ast.If):
            self._expr(stmt.test, dead)
            body = self._block(stmt.body, dead.copy())
            orelse = self._block(stmt.orelse, dead.copy())
            return _DeadSet.intersect([body, orelse])
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            if isinstance(stmt, ast.While):
                self._expr(stmt.test, dead)
            else:
                self._expr(stmt.iter, dead)
                self._bind(stmt.target, dead)
            # two passes: a donation at the body's tail reaches a read
            # at its head on the next iteration
            once = self._block(stmt.body, dead.copy())
            twice = self._block(stmt.body, once.copy())
            after = _DeadSet.intersect([dead, once, twice])
            return self._block(stmt.orelse, after)
        if isinstance(stmt, ast.Try):
            body = self._block(stmt.body, dead.copy())
            outs = [body]
            for handler in stmt.handlers:
                h = _DeadSet.intersect([dead, body])
                if handler.name:
                    h.entries.pop(handler.name, None)
                outs.append(self._block(handler.body, h))
            merged = _DeadSet.intersect(outs)
            merged = self._block(stmt.orelse, merged)
            return self._block(stmt.finalbody, merged)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._expr(item.context_expr, dead)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, dead)
            return self._block(stmt.body, dead)
        if isinstance(stmt, ast.Assign):
            alias = self._alias_source(stmt.value, dead)
            if alias is None:
                self._expr(stmt.value, dead)
            for target in stmt.targets:
                self._bind(target, dead)
                if alias is not None and isinstance(target, ast.Name):
                    dead.entries[target.id] = alias
            return dead
        if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            if stmt.value is not None:
                self._expr(stmt.value, dead)
            if isinstance(stmt, ast.AugAssign):
                # x += v reads x
                self._expr(stmt.target, dead, store_ok=False)
            self._bind(stmt.target, dead)
            return dead
        if isinstance(stmt, ast.Delete):
            for target in stmt.targets:
                if isinstance(target, ast.Name):
                    dead.entries.pop(target.id, None)
                else:
                    self._expr(target, dead)
            return dead
        if isinstance(stmt, (ast.Return, ast.Expr)):
            if stmt.value is not None:
                self._expr(stmt.value, dead)
            return dead
        if isinstance(stmt, ast.ClassDef):
            return dead
        # Raise, Assert, Global, Import, Pass, Break, Continue, ...
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._expr(child, dead)
        return dead

    # -- expression flow

    def _alias_source(
        self, value: ast.expr, dead: _DeadSet
    ) -> Optional[str]:
        """`a = b` where b is a dead bare name: the alias copies the
        handle without touching the buffer — propagate, don't flag."""
        if isinstance(value, ast.Name) and value.id in dead.entries:
            return dead.entries[value.id]
        return None

    def _bind(self, target: ast.expr, dead: _DeadSet) -> None:
        """An assignment target rebinds names: they hold live handles
        again (the rebind-to-outputs discipline)."""
        if isinstance(target, ast.Name):
            dead.entries.pop(target.id, None)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind(elt, dead)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, dead)
        else:
            # obj.attr = v / obj[k] = v: the base expression is read
            self._expr(target, dead, store_ok=True)

    def _expr(self, node: ast.expr, dead: _DeadSet,
              store_ok: bool = False) -> None:
        """Walk an expression: flag reads of dead names, then apply any
        donations its calls perform."""
        if isinstance(node, ast.Call):
            self._call(node, dead)
            return
        if isinstance(node, ast.Name):
            if node.id in dead.entries:
                self._flag(node, dead)
            return
        if isinstance(node, (ast.Lambda, ast.GeneratorExp, ast.ListComp,
                             ast.SetComp, ast.DictComp)):
            # deferred/scoped bodies: comprehension iterables evaluate
            # now, the rest is its own scope — only walk the first iter
            gens = getattr(node, "generators", [])
            if gens:
                self._expr(gens[0].iter, dead)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child, dead)

    def _call(self, call: ast.Call, dead: _DeadSet) -> None:
        # callee expression and every argument are reads first: passing
        # an already-dead name anywhere (donated position or not) is a
        # use-after-donate
        self._expr(call.func, dead)
        for arg in call.args:
            self._expr(arg, dead)
        for kw in call.keywords:
            self._expr(kw.value, dead)

        name = dotted(call.func)
        short = name.rsplit(".", 1)[-1] if name else ""
        donation = self.registry.get(short)
        if donation is None:
            return
        argnums, argnames = donation
        site = f"{short}() at line {call.lineno}"
        for i in argnums:
            if i < len(call.args) and isinstance(call.args[i], ast.Name):
                dead.entries[call.args[i].id] = site
        for kw in call.keywords:
            if (kw.arg in argnames and isinstance(kw.value, ast.Name)):
                dead.entries[kw.value.id] = site

    def _flag(self, node: ast.Name, dead: _DeadSet) -> None:
        site = dead.entries.pop(node.id)  # one finding per donation
        key = (node.lineno, node.col_offset, node.id)
        if key not in self.findings:
            self.findings[key] = self.src.finding(
                "jit-donate-use-after", node,
                f"'{node.id}' was donated into {site} and its device "
                f"buffer is dead; rebind the name from the call's "
                f"outputs before reading it (donation is only a "
                f"warning on CPU — this corrupts on TPU)",
            )


def _check_donate_use_after(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for src in project.in_dirs(*DONATE_SCOPE):
        registry = dict(DONATING_CALLS)
        if src.rel in WRAPPER_SCOPE:
            registry.update(WRAPPER_DONATING_CALLS)
        registry.update(_module_jit_donations(src.tree))
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(_DonateFlow(src, registry).run(node))
    return findings


# --------------------------------------------- conc-await-shared-mutate

AWAIT_MUTATE_SCOPE = (
    "fishnet_tpu/serve",
    "fishnet_tpu/fleet",
    "fishnet_tpu/cache",
)

_SINGLE_WRITER_MARK = "fishnet-lint: single-writer"


def _attr_path(node: ast.expr) -> str:
    """Dotted path of an attribute chain rooted at a bare name
    ('self.stats.chunks_ok', 'member.busy_until'); '' otherwise."""
    return dotted(node)


def _is_lock_name(name: str) -> bool:
    return "lock" in name.lower()


class _AsyncEvents(ast.NodeVisitor):
    """Ordered reads/writes/awaits of one async def's own statements
    (nested defs excluded — they run under to_thread or later)."""

    def __init__(self) -> None:
        self.awaits: List[Tuple[int, int]] = []
        # key -> [(pos, node, lock-ids)]
        self.reads: Dict[str, List[Tuple[Tuple[int, int], ast.AST,
                                         frozenset]]] = {}
        self.writes: Dict[str, List[Tuple[Tuple[int, int], ast.AST,
                                          frozenset]]] = {}
        self._locks: List[int] = []

    def _pos(self, node: ast.AST) -> Tuple[int, int]:
        return (getattr(node, "lineno", 0), getattr(node, "col_offset", 0))

    def _record(self, table, key: str, node: ast.AST) -> None:
        table.setdefault(key, []).append(
            (self._pos(node), node, frozenset(self._locks)))

    # nested functions are their own analysis units
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        pass

    def visit_Lambda(self, node: ast.Lambda) -> None:
        pass

    def visit_Await(self, node: ast.Await) -> None:
        self.awaits.append(self._pos(node))
        self.generic_visit(node)

    def _visit_with(self, node) -> None:
        def ctx_name(expr: ast.expr) -> str:
            if isinstance(expr, ast.Call):
                return dotted(expr.func)
            return dotted(expr)

        locked = any(
            _is_lock_name(ctx_name(item.context_expr))
            for item in node.items
        )
        for item in node.items:
            self.visit(item.context_expr)
        if locked:
            self._locks.append(id(node))
        for stmt in node.body:
            self.visit(stmt)
        if locked:
            self._locks.pop()

    def visit_With(self, node: ast.With) -> None:
        self._visit_with(node)

    def visit_AsyncWith(self, node: ast.AsyncWith) -> None:
        self._visit_with(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        self.visit(node.value)
        for target in node.targets:
            self._target(target)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self.visit(node.value)
        self._target(node.target)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        # atomic read-modify-write between suspension points: the
        # embedded read never straddles an await; the write still can
        self.visit(node.value)
        self._target(node.target, aug=True)

    def visit_Delete(self, node: ast.Delete) -> None:
        for target in node.targets:
            self._target(target)

    def _target(self, target: ast.expr, aug: bool = False) -> None:
        # an augmented target reads and writes at ONE point between
        # suspension points — its value never depends on a pre-await
        # read, so it does not participate in straddle checks
        if isinstance(target, ast.Attribute):
            key = _attr_path(target)
            if key:
                if not aug:
                    self._record(self.writes, key, target)
                return
        if isinstance(target, ast.Subscript):
            key = _attr_path(target.value)
            if key:
                # obj.attr[k] = v mutates the container held by the
                # attribute (the ledger/journal shape)
                if not aug:
                    self._record(self.writes, key, target)
                self.visit(target.slice)
                return
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._target(elt)
            return
        if isinstance(target, ast.Starred):
            self._target(target.value)
            return
        if not isinstance(target, ast.Name):
            self.visit(target)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.ctx, ast.Load):
            key = _attr_path(node)
            if key:
                self._record(self.reads, key, node)
                return  # the inner chain is part of this read
        self.generic_visit(node)


def _single_writer_annotated(src: SourceFile, fn: ast.AST) -> bool:
    line = getattr(fn, "lineno", 1)
    for deco in getattr(fn, "decorator_list", []):
        line = min(line, getattr(deco, "lineno", line))
    for i in (line - 1, line):  # line above the def, and the def line
        if 1 <= i <= len(src.lines) and _SINGLE_WRITER_MARK in src.lines[i - 1]:
            return True
    return False


def _check_await_shared_mutate(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for src in project.in_dirs(*AWAIT_MUTATE_SCOPE):
        for fn in ast.walk(src.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            if _single_writer_annotated(src, fn):
                continue
            events = _AsyncEvents()
            for stmt in fn.body:
                events.visit(stmt)
            if not events.awaits:
                continue
            for key, writes in events.writes.items():
                reads = events.reads.get(key, [])
                if not reads:
                    continue
                for w_pos, w_node, w_locks in writes:
                    straddles = any(
                        r_pos < a_pos < w_pos
                        and not (r_locks & w_locks)
                        for r_pos, _r, r_locks in reads
                        for a_pos in events.awaits
                    )
                    if straddles:
                        findings.append(src.finding(
                            "conc-await-shared-mutate", w_node,
                            f"'{key}' is read before an await and "
                            f"written after it: the written value was "
                            f"computed from state another task may "
                            f"have changed during the suspension. "
                            f"Guard both ends with one lock, move the "
                            f"check next to the write, or annotate "
                            f"the function '# {_SINGLE_WRITER_MARK}' "
                            f"if only this task ever writes it",
                        ))
                        break  # one finding per write site
    return findings


@register_family("dataflow")
def dataflow_rules(project: Project) -> List[Finding]:
    """Donated-buffer lifetime tracking and async check-then-act races."""
    findings = _check_donate_use_after(project)
    findings.extend(_check_await_shared_mutate(project))
    return findings
