"""fishnet-lint: project-invariant static analysis.

Pure-stdlib AST checks for the invariants this codebase depends on but
Python never enforces: trace-safety in the jit kernels, the
FISHNET_TPU_* settings registry contract, dataclass↔serde schema
agreement, and the no-unbounded-blocking discipline of the supervisor
stack. Run as `python -m fishnet_tpu.lint`; see docs/lint.md.
"""
from .core import (  # noqa: F401
    Finding,
    LintResult,
    Project,
    dump_baseline,
    families,
    load_baseline,
    run_lint,
)
