"""Observability clock-discipline rules.

The trace timeline (obs/trace.py), PhaseTracker heartbeats, and every
duration in the hang-forensics path run on `time.monotonic()`. A single
`time.time()` subtraction mixed in silently breaks that contract: an NTP
step mid-run folds the timeline over itself, and the 290 us/step-class
measurements the ROADMAP's perf items depend on become unreproducible.

Rules:
  obs-wall-clock   any `time.time()` call in a file under fishnet_tpu/.
                   Durations and intervals must use time.monotonic() (or
                   the trace clock, obs/trace.py now_us). The sanctioned
                   exception — REPORT timestamps that must correlate
                   with external logs/dashboards (e.g. the sqlite sink's
                   row timestamps in client/stats.py) — is marked inline:
                   `# fishnet-lint: disable=obs-wall-clock`.
"""
from __future__ import annotations

import ast
from typing import List, Set

from .core import Finding, Project, SourceFile, dotted, register_family


def _time_call_sites(src: SourceFile) -> List[ast.Call]:
    """Every call that resolves to stdlib time.time() in this file:
    `time.time()` through `import time` (or an alias), and bare
    `time()` through `from time import time` (or an alias)."""
    mod_aliases: Set[str] = {"time"}
    bare_names: Set[str] = set()
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "time":
                    mod_aliases.add(alias.asname or "time")
        elif isinstance(node, ast.ImportFrom):
            if node.module == "time" and not node.level:
                for alias in node.names:
                    if alias.name == "time":
                        bare_names.add(alias.asname or "time")

    sites: List[ast.Call] = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr == "time":
            if dotted(fn.value) in mod_aliases:
                sites.append(node)
        elif isinstance(fn, ast.Name) and fn.id in bare_names:
            sites.append(node)
    return sites


@register_family("obs")
def check_obs_clock(project: Project) -> List[Finding]:
    """Clock discipline: wall clock never measures durations."""
    findings: List[Finding] = []
    for src in project.in_dirs("fishnet_tpu"):
        for node in _time_call_sites(src):
            findings.append(src.finding(
                "obs-wall-clock", node,
                "time.time() is wall clock — an NTP step skews every "
                "duration and hang timeline derived from it; use "
                "time.monotonic() (or the trace clock, obs/trace.py). "
                "Report-timestamp sites that must match external logs "
                "suppress inline with "
                "`# fishnet-lint: disable=obs-wall-clock`",
            ))
    return findings
