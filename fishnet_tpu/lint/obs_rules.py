"""Observability clock-discipline rules.

The trace timeline (obs/trace.py), PhaseTracker heartbeats, and every
duration in the hang-forensics path run on `time.monotonic()`. A single
`time.time()` subtraction mixed in silently breaks that contract: an NTP
step mid-run folds the timeline over itself, and the 290 us/step-class
measurements the ROADMAP's perf items depend on become unreproducible.

Rules:
  obs-wall-clock   any `time.time()` call in a file under fishnet_tpu/
                   or in tools/loadgen.py (whose latency percentiles and
                   arrival offsets feed the same reports).
                   Durations and intervals must use time.monotonic() (or
                   the trace clock, obs/trace.py now_us). The sanctioned
                   exception — REPORT timestamps that must correlate
                   with external logs/dashboards (e.g. the sqlite sink's
                   row timestamps in client/stats.py) — is marked inline:
                   `# fishnet-lint: disable=obs-wall-clock`.
  obs-orphan-span  a frame/dispatch site that hands work across a
                   process boundary without propagating the request
                   context (obs/trace.py CTX_KEYS). A hop that drops ctx
                   orphans every downstream span — the request's causal
                   chain dead-ends at that boundary and trace_report
                   --request can no longer stitch the waterfall. Three
                   site shapes are checked: a `"t": "partial"` frame
                   built in a function that never touches ctx; a
                   `"t": "go"` frame whose "chunk" payload is not
                   serialized by chunk_to_wire (which carries each
                   WorkPosition's ctx, proven by the wire-schema lint);
                   and a ServeRequest(...) construction without the
                   position_ctx field.
  obs-metric-name  every name registered on the MetricsRegistry
                   (counter/gauge/histogram on a REGISTRY/registry/reg
                   receiver, plus absorb_totals prefixes) must follow
                   the exported-namespace grammar `fishnet_[a-z0-9_]+`
                   and the unit-suffix convention: counters carry a
                   `_total` token, histograms a `_ms`/`_seconds`/
                   `_bytes` unit token (`_ratio` for dimensionless
                   shares). Gauges are charset-only —
                   point-in-time ratios/levels (`fishnet_lanes_live`,
                   `fishnet_cache_hit_ratio_*`) have no natural unit,
                   and mirrored externally-kept totals
                   (`fishnet_fleet_members_total`) keep their source
                   name. Names the registry would have to mangle
                   (_sanitize) or that land outside the `fishnet_`
                   namespace never reach a dashboard query unscathed;
                   the perf ledger joins on these exact strings.
                   F-string names are checked on their literal
                   fragments; one with a leading interpolation (the
                   SloRecorder `{self.prefix}_...` family) is the
                   caller's namespace choice and is skipped.
"""
from __future__ import annotations

import ast
import re
from typing import List, Optional, Set, Tuple

from .core import Finding, Project, SourceFile, dotted, register_family


def _time_call_sites(src: SourceFile) -> List[ast.Call]:
    """Every call that resolves to stdlib time.time() in this file:
    `time.time()` through `import time` (or an alias), and bare
    `time()` through `from time import time` (or an alias)."""
    mod_aliases: Set[str] = {"time"}
    bare_names: Set[str] = set()
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "time":
                    mod_aliases.add(alias.asname or "time")
        elif isinstance(node, ast.ImportFrom):
            if node.module == "time" and not node.level:
                for alias in node.names:
                    if alias.name == "time":
                        bare_names.add(alias.asname or "time")

    sites: List[ast.Call] = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr == "time":
            if dotted(fn.value) in mod_aliases:
                sites.append(node)
        elif isinstance(fn, ast.Name) and fn.id in bare_names:
            sites.append(node)
    return sites


def _dict_key(node: ast.Dict, key: str) -> Optional[ast.AST]:
    """Value expression for a constant string key in a dict literal."""
    for k, v in zip(node.keys, node.values):
        if isinstance(k, ast.Constant) and k.value == key:
            return v
    return None


def _mentions_ctx(fn: Optional[ast.AST]) -> bool:
    """Does this function touch the request-context field at all? Any
    spelling counts — the `ctx`/`position_ctx` name, a `.ctx` attribute,
    or the "ctx" string key (`wp.get("ctx")`, `frame["ctx"]`)."""
    if fn is None:
        return False
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and node.id in ("ctx", "position_ctx"):
            return True
        if isinstance(node, ast.Attribute) and node.attr == "ctx":
            return True
        if isinstance(node, ast.Constant) and node.value == "ctx":
            return True
    return False


def _last_component(name: str) -> str:
    return name.rsplit(".", 1)[-1] if name else ""


def _dispatch_sites(
    src: SourceFile,
) -> List[Tuple[str, ast.AST, Optional[ast.AST]]]:
    """(kind, node, enclosing function) for every cross-process hand-off
    in this file: work-carrying pipe frames and serve dispatch bodies."""
    sites: List[Tuple[str, ast.AST, Optional[ast.AST]]] = []

    def visit(node: ast.AST, fn: Optional[ast.AST]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn = node
        if isinstance(node, ast.Dict):
            tag = _dict_key(node, "t")
            if isinstance(tag, ast.Constant):
                if tag.value == "partial":
                    sites.append(("partial", node, fn))
                elif tag.value == "go" and _dict_key(node, "chunk") is not None:
                    sites.append(("go", node, fn))
        if isinstance(node, ast.Call):
            if _last_component(dotted(node.func)) == "ServeRequest":
                sites.append(("serve-request", node, fn))
        for child in ast.iter_child_nodes(node):
            visit(child, fn)

    visit(src.tree, None)
    return sites


@register_family("obs")
def check_obs_orphan_span(project: Project) -> List[Finding]:
    """Context propagation: no hop across a process boundary may drop
    the request context."""
    findings: List[Finding] = []
    for src in project.in_dirs("fishnet_tpu", "tools/loadgen.py"):
        for kind, node, fn in _dispatch_sites(src):
            if kind == "partial":
                if _mentions_ctx(fn):
                    continue
                msg = (
                    "per-position `partial` frame built without touching "
                    "the request context — a replayed position loses its "
                    "trace here; forward `wp.ctx` into the frame "
                    "(engine/host.py emit_partial is the reference shape)"
                )
            elif kind == "go":
                chunk = _dict_key(node, "chunk")
                if (isinstance(chunk, ast.Call) and _last_component(
                        dotted(chunk.func)) == "chunk_to_wire"):
                    continue  # the wire schema carries per-position ctx
                if _mentions_ctx(fn):
                    continue
                msg = (
                    "`go` frame ships a chunk payload not serialized by "
                    "chunk_to_wire — every position crosses the pipe "
                    "without its request context and the trace dead-ends "
                    "at this hop"
                )
            else:  # serve-request
                if any(kw.arg == "position_ctx" or kw.arg is None
                       for kw in node.keywords):
                    continue  # explicit ctx (or a **splat we can't see into)
                msg = (
                    "ServeRequest built without position_ctx — the HTTP "
                    "dispatch hop drops every position's request context "
                    "and the remote edge mints a fresh trace_id instead "
                    "of continuing the caller's; forward "
                    "`position_ctx=...` (fleet/remote.py "
                    "chunk_to_serve_request is the reference shape)"
                )
            findings.append(src.finding("obs-orphan-span", node, msg))
    return findings


# ----------------------------------------------------------- metric names

# the exported-namespace grammar every registered metric name obeys
_METRIC_NAME_RE = re.compile(r"^fishnet_[a-z0-9_]+$")
# charset a literal f-string fragment may use (interpolations fill the
# rest; the registry's _sanitize would mangle anything else)
_METRIC_FRAGMENT_RE = re.compile(r"^[a-z0-9_]*$")
# registry receivers; excludes the trace recorder (`rec.counter(...)`
# in engine/tpu.py emits trace counter events, a different namespace)
_REGISTRY_RECEIVERS = {"REGISTRY", "registry", "reg"}
_METRIC_KINDS = {"counter", "gauge", "histogram"}
_HISTOGRAM_UNITS = {"ms", "seconds", "bytes", "ratio"}


def _metric_name_tokens(node: ast.AST) -> Optional[Set[str]]:
    """The `_`-split tokens of a metric-name expression's literal text,
    or None when the expression can't be charset/unit checked (a
    variable, or an f-string led by an interpolation). Raises ValueError
    with a reason when a literal violates the grammar."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        if not _METRIC_NAME_RE.match(node.value):
            raise ValueError(
                f'"{node.value}" is outside the metric grammar '
                "fishnet_[a-z0-9_]+"
            )
        return {t for t in node.value.split("_") if t}
    if isinstance(node, ast.JoinedStr):
        if not node.values or isinstance(node.values[0], ast.FormattedValue):
            return None  # leading interpolation: namespace is the caller's
        tokens: Set[str] = set()
        for i, piece in enumerate(node.values):
            if not (isinstance(piece, ast.Constant)
                    and isinstance(piece.value, str)):
                continue
            frag = piece.value
            if i == 0:
                if not frag.startswith("fishnet_"):
                    raise ValueError(
                        f'f-string metric name starts with "{frag}" — '
                        "exported names live in the fishnet_ namespace"
                    )
            if not _METRIC_FRAGMENT_RE.match(frag):
                raise ValueError(
                    f'f-string fragment "{frag}" is outside the metric '
                    "charset [a-z0-9_]"
                )
            tokens.update(t for t in frag.split("_") if t)
        return tokens
    return None  # dynamic name; nothing checkable statically


def _metric_sites(src: SourceFile) -> List[Tuple[str, ast.Call]]:
    """(kind, call) for every registry registration in this file:
    counter/gauge/histogram on a REGISTRY-shaped receiver, and
    absorb_totals (whose prefix becomes `{prefix}_{key}` gauge/counter
    names) on any receiver."""
    sites: List[Tuple[str, ast.Call]] = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            continue
        if fn.attr == "absorb_totals":
            sites.append(("absorb_totals", node))
        elif fn.attr in _METRIC_KINDS:
            if _last_component(dotted(fn.value)) in _REGISTRY_RECEIVERS:
                sites.append((fn.attr, node))
    return sites


@register_family("obs")
def check_obs_metric_name(project: Project) -> List[Finding]:
    """Metric-name discipline: the exported namespace grammar plus the
    per-kind unit-suffix convention (see module docstring)."""
    findings: List[Finding] = []
    for src in project.in_dirs("fishnet_tpu", "tools", "bench.py"):
        for kind, call in _metric_sites(src):
            if not call.args:
                continue
            try:
                tokens = _metric_name_tokens(call.args[0])
            except ValueError as e:
                findings.append(src.finding(
                    "obs-metric-name", call,
                    f"{e} — dashboards and the perf ledger join on the "
                    "exact exported string",
                ))
                continue
            if tokens is None:
                continue
            if kind == "counter" and "total" not in tokens:
                findings.append(src.finding(
                    "obs-metric-name", call,
                    "counter without a _total token — Prometheus "
                    "convention marks monotonic series with _total; "
                    "rate() queries and the perf direction table key "
                    "off it",
                ))
            elif kind == "histogram" and not (tokens & _HISTOGRAM_UNITS):
                findings.append(src.finding(
                    "obs-metric-name", call,
                    "histogram without a unit token (_ms/_seconds/"
                    "_bytes, or _ratio for dimensionless shares) — "
                    "bucket bounds are meaningless without the unit "
                    "in the name",
                ))
    return findings


@register_family("obs")
def check_obs_clock(project: Project) -> List[Finding]:
    """Clock discipline: wall clock never measures durations."""
    findings: List[Finding] = []
    for src in project.in_dirs("fishnet_tpu", "tools/loadgen.py"):
        for node in _time_call_sites(src):
            findings.append(src.finding(
                "obs-wall-clock", node,
                "time.time() is wall clock — an NTP step skews every "
                "duration and hang timeline derived from it; use "
                "time.monotonic() (or the trace clock, obs/trace.py). "
                "Report-timestamp sites that must match external logs "
                "suppress inline with "
                "`# fishnet-lint: disable=obs-wall-clock`",
            ))
    return findings
