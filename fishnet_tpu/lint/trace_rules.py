"""Trace-safety rules for the jit-traced kernels.

The kernels in ops/, models/, and engine/tpu.py rely on invariants that
nothing enforces until trace time on hardware: no host synchronization
inside traced code, no numpy applied to traced values, no Python control
flow on traced expressions, and explicit dtypes on integer constructors
(the uint64-bitboards-as-int32-bits discipline in ops/board.py breaks
silently if a constructor picks a platform-dependent default).

Scoping: a function is considered *traced* when it is (a) decorated with
or wrapped by `jax.jit`, (b) passed to a `lax` control-flow combinator
(while_loop/scan/cond/fori_loop/switch), (c) defined inside a traced
function, (d) called (by simple name, intra-module) from a traced
function, or (e) annotated with a `# fishnet-lint: traced` comment on
the line above its `def`. Host-side drivers (iterative deepening,
result extraction) in the same files are deliberately out of scope —
`.item()` and `int()` are their job.

Rules:
  trace-host-item   .item()/.tolist() inside a traced function
  trace-host-cast   int()/float()/bool() on a non-literal inside a
                    traced function (host cast → trace error on device)
  trace-np-mix      np.* applied to a jnp-derived expression inside a
                    traced function
  trace-py-branch   Python if/while/assert testing a jnp expression
                    inside a traced function (use lax.cond/jnp.where)
  trace-sync        .block_until_ready() in a trace-scoped file outside
                    the allowlisted host-sync functions
  trace-int-dtype   jnp.arange/zeros/ones/full/empty without an
                    explicit dtype anywhere in a trace-scoped file
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from .core import (
    Finding,
    Project,
    SourceFile,
    call_name,
    dotted,
    has_kwarg,
    register_family,
)

TRACE_SCOPE = ("fishnet_tpu/ops", "fishnet_tpu/models", "fishnet_tpu/engine/tpu.py")

# functions (by simple name) where a host sync is sanctioned even inside
# trace-scoped files — extend deliberately, with a comment, or suppress
# inline at the call site
SYNC_ALLOWLIST: Set[str] = set()

_TRACED_MARK_RE = re.compile(r"#\s*fishnet-lint:\s*traced\b")

# dtype-less constructors whose default dtype is contextual; index of the
# positional arg that would carry dtype
_CTORS = {
    "arange": 3,
    "zeros": 1,
    "ones": 1,
    "empty": 1,
    "full": 2,
}

_LAX_HOFS = {
    "while_loop": (0, 1),
    "scan": (0,),
    "cond": (1, 2),
    "fori_loop": (2,),
    "switch": None,  # every arg past the index may be a branch callable
}


class _FunctionInfo:
    def __init__(self, node: ast.AST, parent: Optional["_FunctionInfo"]) -> None:
        self.node = node
        self.parent = parent
        self.name = getattr(node, "name", "<lambda>")
        self.calls: Set[str] = set()
        self.traced = False


def _index_functions(src: SourceFile):
    """Map every function/lambda node to its info, recording parenthood
    and intra-module simple-name call edges."""
    infos: Dict[ast.AST, _FunctionInfo] = {}
    by_name: Dict[str, List[_FunctionInfo]] = {}

    def visit(node: ast.AST, parent: Optional[_FunctionInfo]) -> None:
        info = parent
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            info = _FunctionInfo(node, parent)
            infos[node] = info
            if not isinstance(node, ast.Lambda):
                by_name.setdefault(node.name, []).append(info)
        if isinstance(node, ast.Call) and info is not None:
            name = call_name(node)
            if name:
                info.calls.add(name.split(".")[-1])
        for child in ast.iter_child_nodes(node):
            visit(child, info)

    visit(src.tree, None)
    return infos, by_name


def _is_jit_expr(node: ast.AST) -> bool:
    """`jax.jit`, `jit`, or `partial(jax.jit, ...)`-style expressions."""
    name = dotted(node)
    if name in ("jit", "jax.jit", "nn.jit"):
        return True
    if isinstance(node, ast.Call):
        fn = dotted(node.func)
        if fn.split(".")[-1] == "partial" and node.args:
            return _is_jit_expr(node.args[0])
        return _is_jit_expr(node.func)
    return False


def _mark_roots(src: SourceFile, infos, by_name) -> None:
    def mark_name(simple: str) -> None:
        for info in by_name.get(simple, []):
            info.traced = True

    def mark_arg(arg: ast.AST) -> None:
        if isinstance(arg, ast.Name):
            mark_name(arg.id)
        elif isinstance(arg, ast.Lambda) and arg in infos:
            infos[arg].traced = True

    for node in ast.walk(src.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                if _is_jit_expr(deco):
                    infos[node].traced = True
            # explicit annotation: `# fishnet-lint: traced` above the def
            deco_line = min(
                [node.lineno] + [d.lineno for d in node.decorator_list]
            )
            above = src.source_at(deco_line - 1)
            if _TRACED_MARK_RE.search(above):
                infos[node].traced = True
        elif isinstance(node, ast.Call):
            target = call_name(node)
            simple = target.split(".")[-1]
            if _is_jit_expr(node.func):
                for arg in node.args[:1]:
                    mark_arg(arg)
            elif simple in _LAX_HOFS and (
                target.startswith("lax.") or target.startswith("jax.lax.")
                or target == simple
            ):
                positions = _LAX_HOFS[simple]
                if positions is None:
                    for arg in node.args:
                        mark_arg(arg)
                else:
                    for i in positions:
                        if i < len(node.args):
                            mark_arg(node.args[i])


def _propagate(infos, by_name) -> None:
    # nested-in-traced plus intra-module call edges, to fixpoint
    changed = True
    while changed:
        changed = False
        for info in infos.values():
            if not info.traced and info.parent is not None and info.parent.traced:
                info.traced = True
                changed = True
            if info.traced:
                for callee in info.calls:
                    for target in by_name.get(callee, []):
                        if not target.traced:
                            target.traced = True
                            changed = True


def _contains_jnp(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Attribute, ast.Name)):
            name = dotted(sub)
            if name.startswith("jnp.") or name.startswith("jax.numpy."):
                return True
    return False


def _jnp_tainted_names(fn_node: ast.AST) -> Set[str]:
    """Names assigned (directly) from a jnp.* expression within fn."""
    tainted: Set[str] = set()
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Assign) and _contains_jnp(node.value):
            for target in node.targets:
                if isinstance(target, ast.Name):
                    tainted.add(target.id)
        elif isinstance(node, ast.AugAssign) and _contains_jnp(node.value):
            if isinstance(node.target, ast.Name):
                tainted.add(node.target.id)
    return tainted


@register_family("trace")
def check_trace_safety(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for src in project.in_dirs(*TRACE_SCOPE):
        infos, by_name = _index_functions(src)
        _mark_roots(src, infos, by_name)
        _propagate(infos, by_name)

        # map every AST node to its innermost enclosing function info
        node_fn: Dict[ast.AST, _FunctionInfo] = {}

        def assign(node, current):
            if node in infos:
                current = infos[node]
            node_fn[node] = current
            for child in ast.iter_child_nodes(node):
                assign(child, current)

        assign(src.tree, None)

        taint_cache: Dict[ast.AST, Set[str]] = {}

        for node in ast.walk(src.tree):
            fn = node_fn.get(node)
            traced = fn is not None and fn.traced

            if isinstance(node, ast.Call):
                name = call_name(node)
                simple = name.split(".")[-1]

                if traced and isinstance(node.func, ast.Attribute) and \
                        node.func.attr in ("item", "tolist") and not node.args:
                    findings.append(src.finding(
                        "trace-host-item", node,
                        f".{node.func.attr}() forces a host sync and fails "
                        "under trace; keep device values on device",
                    ))

                if traced and isinstance(node.func, ast.Name) and \
                        node.func.id in ("int", "float", "bool") and \
                        len(node.args) == 1 and \
                        not isinstance(node.args[0], ast.Constant):
                    findings.append(src.finding(
                        "trace-host-cast", node,
                        f"{node.func.id}() on a traced value is a host cast; "
                        "use .astype()/jnp casts inside traced code",
                    ))

                if traced and name.startswith("np.") and node.args:
                    root = fn.node
                    if root not in taint_cache:
                        taint_cache[root] = _jnp_tainted_names(root)
                    tainted = taint_cache[root]
                    for arg in node.args:
                        if _contains_jnp(arg) or (
                            isinstance(arg, ast.Name) and arg.id in tainted
                        ):
                            findings.append(src.finding(
                                "trace-np-mix", node,
                                f"{name}(...) applied to a jnp value inside "
                                "traced code concretizes the tracer; use the "
                                "jnp equivalent",
                            ))
                            break

                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "block_until_ready":
                    fname = fn.name if fn is not None else "<module>"
                    if fname not in SYNC_ALLOWLIST:
                        findings.append(src.finding(
                            "trace-sync", node,
                            "block_until_ready() outside the allowlist; host "
                            "syncs belong in benchmarks and allowlisted "
                            "drivers (lint/trace_rules.py SYNC_ALLOWLIST)",
                        ))

                if name.startswith("jnp.") and simple in _CTORS:
                    dtype_pos = _CTORS[simple]
                    if not has_kwarg(node, "dtype") and \
                            len(node.args) <= dtype_pos:
                        findings.append(src.finding(
                            "trace-int-dtype", node,
                            f"jnp.{simple}(...) without an explicit dtype; "
                            "the int32-bits discipline requires explicit "
                            "dtypes on constructors in kernel files",
                        ))

            elif isinstance(node, (ast.If, ast.While, ast.Assert)) and traced:
                # `x is None` never inspects a traced value (tracers are
                # never None) — the idiomatic optional-arg default branch
                # in init paths is fine under trace
                if isinstance(node.test, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot))
                    for op in node.test.ops
                ):
                    continue
                root = fn.node
                if root not in taint_cache:
                    taint_cache[root] = _jnp_tainted_names(root)
                tainted = taint_cache[root]
                on_traced = _contains_jnp(node.test) or any(
                    isinstance(sub, ast.Name) and sub.id in tainted
                    for sub in ast.walk(node.test)
                )
                if on_traced and isinstance(node, ast.Assert):
                    findings.append(src.finding(
                        "trace-py-branch", node,
                        "assert on a jnp expression inside traced code "
                        "fails at trace time; use checkify or a host check",
                    ))
                elif on_traced:
                    findings.append(src.finding(
                        "trace-py-branch", node,
                        "Python control flow on a jnp expression inside "
                        "traced code; use lax.cond/lax.while_loop/jnp.where",
                    ))
    return findings
