"""Mesh-sharding discipline rules.

The partition-rule registry (parallel/partition.py) is the ONE place
layout decisions live: every shard_map in/out spec, NamedSharding and
PartitionSpec the engine uses derives from its rule table, so
single-host, forced-multi-device and multi-host jax.distributed meshes
stay one data-driven code path. A hand-built spec anywhere else is a
layout decision the registry cannot see — it drifts silently when a
state field is added or an axis is renamed, and on a multi-host mesh a
divergent spec deadlocks or corrupts instead of failing loudly.

Rules:
  mesh-unregistered-spec   any call that resolves to
                           jax.sharding.PartitionSpec / NamedSharding
                           (any import-alias form, including
                           `from jax.sharding import PartitionSpec as P`)
                           or to shard_map (jax.shard_map or
                           jax.experimental.shard_map.shard_map) — in
                           any package/tool file other than
                           parallel/partition.py and parallel/mesh.py.
"""
from __future__ import annotations

import ast
from typing import List, Set

from .core import Finding, Project, SourceFile, dotted, register_family

# the two files allowed to construct sharding specs directly
_ALLOWED = (
    "fishnet_tpu/parallel/partition.py",
    "fishnet_tpu/parallel/mesh.py",
)

_SHARDING_MODULE = "jax.sharding"
_SPEC_NAMES = {"PartitionSpec", "NamedSharding"}
_SHARD_MAP_MODULE = "jax.experimental.shard_map"


def _spec_call_sites(src: SourceFile) -> List[ast.Call]:
    """Every call in this file that resolves to a sharding-spec
    constructor (PartitionSpec/NamedSharding through any import form of
    jax.sharding) or to shard_map (jax.shard_map attribute access, or
    any import form of jax.experimental.shard_map.shard_map)."""
    shard_mod_aliases: Set[str] = set()  # alias -> jax.sharding module
    sm_mod_aliases: Set[str] = set()     # alias -> ...shard_map module
    jax_aliases: Set[str] = set()        # alias -> jax itself
    bare_specs: Set[str] = set()         # from-imported spec constructors
    bare_shard_map: Set[str] = set()     # from-imported shard_map fn
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == _SHARDING_MODULE:
                    shard_mod_aliases.add(alias.asname or alias.name)
                elif alias.name == _SHARD_MAP_MODULE:
                    sm_mod_aliases.add(alias.asname or alias.name)
                elif alias.name == "jax":
                    jax_aliases.add(alias.asname or alias.name)
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                continue
            if node.module == _SHARDING_MODULE:
                for alias in node.names:
                    if alias.name in _SPEC_NAMES:
                        bare_specs.add(alias.asname or alias.name)
            elif node.module == "jax":
                for alias in node.names:
                    if alias.name == "sharding":
                        shard_mod_aliases.add(alias.asname or alias.name)
                    elif alias.name == "shard_map":
                        bare_shard_map.add(alias.asname or alias.name)
            elif node.module == "jax.experimental":
                for alias in node.names:
                    if alias.name == "shard_map":
                        sm_mod_aliases.add(alias.asname or alias.name)
            elif node.module == _SHARD_MAP_MODULE:
                for alias in node.names:
                    if alias.name == "shard_map":
                        bare_shard_map.add(alias.asname or alias.name)

    sites: List[ast.Call] = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        if not name:
            continue
        head, _, tail = name.rpartition(".")
        if name in bare_specs or name in bare_shard_map:
            sites.append(node)
        elif head in shard_mod_aliases and tail in _SPEC_NAMES:
            sites.append(node)
        elif head in sm_mod_aliases and tail == "shard_map":
            sites.append(node)
        elif head in jax_aliases and tail == "shard_map":
            sites.append(node)  # jax.shard_map (new-style alias)
        elif (head.split(".", 1)[0] in jax_aliases
              and name.endswith(".sharding." + tail)
              and tail in _SPEC_NAMES):
            sites.append(node)  # jax.sharding.PartitionSpec(...)
    return sites


@register_family("mesh")
def check_mesh_registered_specs(project: Project) -> List[Finding]:
    """Sharding specs stay behind the partition-rule registry."""
    findings: List[Finding] = []
    for src in project.in_dirs("fishnet_tpu", "tools", "bench.py"):
        if src.rel in _ALLOWED:
            continue
        for node in _spec_call_sites(src):
            findings.append(src.finding(
                "mesh-unregistered-spec", node,
                "hand-built sharding spec outside parallel/partition.py "
                "+ parallel/mesh.py — a layout decision the partition-"
                "rule registry cannot see, which drifts silently when "
                "state fields or mesh topology change; derive it from "
                "the registry (match_partition_rules / segment_specs / "
                "named_sharding in fishnet_tpu/parallel/partition.py)",
            ))
    return findings
