"""Analysis-cache key discipline rules.

A cache entry is only safe to serve when its key captures everything
that changes the answer and normalizes everything that doesn't
(fishnet_tpu/cache/keys.py: content-only fingerprint, raw multipv,
EFFECTIVE node budget, engine identity). A `CacheKey(...)` hand-built
anywhere else skips that normalization: the serve layer and the fleet
coordinator stop agreeing on keys, which reads as a miss at best — and
at worst stores an entry under a shape it doesn't answer, i.e. a stale
hit. All key construction must route through the builders in
fishnet_tpu/cache/keys.py (`key_for_chunk_position`,
`keys_for_requests`, `key_for_request`).

Rules:
  cache-unkeyed-store  any call that resolves to the CacheKey
                       constructor — through any import form of
                       fishnet_tpu.cache / fishnet_tpu.cache.keys —
                       in any package/tool file other than the cache
                       package's own keys.py/store.py (store.py
                       rebuilds keys from its persisted index).
"""
from __future__ import annotations

import ast
from typing import List, Set

from .core import Finding, Project, SourceFile, dotted, register_family

# the files allowed to construct CacheKey directly: the builders, and
# the store (which reconstructs keys from its sqlite index rows)
_ALLOWED = ("fishnet_tpu/cache/keys.py", "fishnet_tpu/cache/store.py")

# module-path tails that mean "the cache package", across absolute and
# relative import spellings
_KEY_MODULE_TAILS = ("cache", "cache.keys")


def _is_cache_module(module: str) -> bool:
    return any(
        module == tail or module.endswith("." + tail)
        for tail in _KEY_MODULE_TAILS
    )


def _key_call_sites(src: SourceFile) -> List[ast.Call]:
    """Every call in this file that resolves to the CacheKey
    constructor, through any import form of the cache package."""
    mod_aliases: Set[str] = set()  # alias -> the cache (sub)module
    bare_names: Set[str] = set()  # from-imported CacheKey
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if _is_cache_module(alias.name):
                    mod_aliases.add(alias.asname or alias.name)
        elif isinstance(node, ast.ImportFrom):
            module = node.module or ""
            if _is_cache_module(module):
                for alias in node.names:
                    if alias.name == "CacheKey":
                        bare_names.add(alias.asname or alias.name)
                    elif alias.name == "keys":
                        mod_aliases.add(alias.asname or alias.name)
            else:
                # `from fishnet_tpu import cache` / `from .. import cache`
                for alias in node.names:
                    if alias.name == "cache":
                        mod_aliases.add(alias.asname or alias.name)

    sites: List[ast.Call] = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        if not name:
            continue
        head, _, tail = name.rpartition(".")
        if name in bare_names:
            sites.append(node)
        elif tail == "CacheKey" and head and (
            head in mod_aliases
            or any(head.startswith(m + ".") for m in mod_aliases)
            or _is_cache_module(head)
        ):
            sites.append(node)
    return sites


@register_family("cache")
def check_cache_keyed_store(project: Project) -> List[Finding]:
    """Cache keys stay behind the canonical builders."""
    findings: List[Finding] = []
    for src in project.in_dirs("fishnet_tpu", "tools", "bench.py"):
        if src.rel in _ALLOWED:
            continue
        for node in _key_call_sites(src):
            findings.append(src.finding(
                "cache-unkeyed-store", node,
                "hand-built CacheKey outside cache/keys.py skips the "
                "normalization the satisfaction rule depends on "
                "(content fingerprint, effective node budget, engine "
                "identity) — the serve and fleet layers stop agreeing "
                "on keys and a stale hit becomes possible; build keys "
                "via key_for_chunk_position / keys_for_requests",
            ))
    return findings
