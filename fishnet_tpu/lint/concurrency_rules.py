"""Concurrency rules for the supervisor/worker/queue machinery.

The supervisor exists because a wedged device must never wedge the
client (docs/tpu-hang.md); these rules keep the discipline that makes
that true:

  conc-no-timeout      .join()/.get()/.wait()/.recv() with no timeout
                       and no surrounding asyncio.wait_for — an
                       unbounded block on a peer that may be wedged
  conc-block-in-lock   a known-blocking call inside `with <lock>:` —
                       one stalled peer stalls every lock waiter
  conc-bare-except     `except:` catches SystemExit/KeyboardInterrupt
  conc-swallow-base    `except BaseException:` without a re-raise
  conc-silent-except   a broad handler (Exception/BaseException/bare)
                       whose body neither logs nor raises — failures
                       vanish without a trace
  conc-host-sync       a blocking host sync (int(), np.asarray(),
                       .block_until_ready(), jax.device_get) applied to
                       a device-resident value inside the scheduler
                       loop — every such sync stalls the pipeline and
                       escapes the SyncStats transfer accounting
  conc-journal-writer  the supervisor's session journal
                       (self._journal / self._journal_expect) mutated
                       outside its delivery path — the recovery ladder
                       trusts exactly-once journal contents, so the
                       single-writer invariant allows mutation only in
                       _journal_record/_journal_reset/__init__
  conc-sock-in-loop    a known-blocking socket/IO call (socket.*,
                       time.sleep, urllib, http.client) inside an
                       `async def` of the serving package — one blocked
                       handler freezes every connection the event loop
                       owns; use asyncio streams / asyncio.sleep /
                       run_in_executor instead
  conc-unbounded-retry an unbounded loop (`while True`, for-over-
                       itertools.count) that awaits a network call and
                       catches transport-level failures back into the
                       next iteration — a dead peer spins the retry
                       forever; bound it with an attempt cap
                       (`for attempt in range(N)`) or a deadline guard
                       that breaks/raises (fleet/remote.py's in-dispatch
                       retry is the canonical shape)

Scopes: the timeout/lock rules run on the process-boundary modules
(supervisor, host, uci, workers, queue), on fishnet_tpu/serve/ (the
HTTP front-end is a process boundary too), on fishnet_tpu/fleet/
(the coordinator fans out across N member processes/machines), and on
fishnet_tpu/aot/ (registry export threads and flush() joins sit on the
engine boot path); the except rules run on all of client/, engine/,
serve/, fleet/ and aot/ (kernels and utils keep their own idioms —
e.g. compile_cache deliberately degrades to "no cache" on any error).
The sock-in-loop rule runs on serve/ and fleet/ — the packages whose
code lives inside a single shared event loop.
Narrow handlers (`except OSError: pass` around best-effort logging) are
deliberately not flagged — the rules target *broad* swallowing.

The host-sync rule runs on the scheduler-loop modules (engine/tpu.py's
LaneScheduler and ops/search.py's stream/batch loops): values that
flow from the segment dispatch jits (`_run_segment_jit`,
`_init_state_jit`, `_merge_lanes_jit`, `refill_lanes`,
`extract_results`, the shard_map'd mesh callables
`run_segment_sharded`/`refill_lanes_sharded`, or a local
`dispatch`/`flush_adm` wrapper) are device-resident, and the only
sanctioned way to materialize one on the host inside a `while` loop is
`SyncStats.fetch`, which counts the transfer and measures the blocked
time (utils/syncstats.py).
`stats.fetch(x)` is naturally absolved — the rule tracks the names, and
a fetch result is a host value, not a device one.
"""
from __future__ import annotations

import ast
from typing import List

from .core import (
    Finding,
    Project,
    dotted,
    register_family,
)

# modules where an unbounded block is a liveness bug. fishnet_tpu/aot
# is in scope: the registry's export threads and flush() joins sit on
# the engine boot path, and an unbounded wait there wedges warmup.
# fishnet_tpu/fleet covers the autoscaler (fleet/autoscaler.py) by
# prefix; tools/loadgen.py is named explicitly — its open-loop firing
# task shares the client event loop, so the same liveness rules apply
BLOCK_SCOPE = (
    "fishnet_tpu/engine/supervisor.py",
    "fishnet_tpu/engine/host.py",
    "fishnet_tpu/engine/uci.py",
    "fishnet_tpu/client/workers.py",
    "fishnet_tpu/client/queue.py",
    "fishnet_tpu/serve",
    "fishnet_tpu/fleet",
    "fishnet_tpu/aot",
    "fishnet_tpu/cache",
    "tools/loadgen.py",
)

# modules where a swallowed exception hides an operational failure
EXCEPT_SCOPE = ("fishnet_tpu/client", "fishnet_tpu/engine",
                "fishnet_tpu/serve", "fishnet_tpu/fleet",
                "fishnet_tpu/aot", "fishnet_tpu/cache",
                "tools/loadgen.py")

# these packages run inside ONE shared event loop: a blocking socket
# call in an async def stalls every tenant (serve), every member
# dispatch (fleet — the autoscaler control loop rides the same loop),
# or every open-loop arrival (tools/loadgen.py) at once
SERVE_ASYNC_SCOPE = ("fishnet_tpu/serve", "fishnet_tpu/fleet",
                     "fishnet_tpu/cache", "tools/loadgen.py")

# call targets that block the thread: raw socket ops, sync HTTP
# clients, and the sleep that should have been asyncio.sleep. Matched
# against the dotted call name: exact for the module-level forms,
# attribute-tail for the socket-object methods (asyncio stream APIs —
# read/readline/readexactly/write/drain — are deliberately absent)
_BLOCKING_IN_LOOP_EXACT = ("time.sleep", "socket.socket",
                           "socket.create_connection", "socket.getaddrinfo",
                           "urllib.request.urlopen")
_BLOCKING_IN_LOOP_TAILS = ("accept", "connect", "recv", "recv_into",
                           "sendall", "makefile", "urlopen",
                           "HTTPConnection", "HTTPSConnection")

# modules that talk to peers over the wire: an unbounded retry loop
# here turns one dead peer into a coroutine that spins forever.
# tools/loadgen.py is open-loop BY CONTRACT — a retry loop there would
# silently convert it to closed-loop — so the same rule polices it
RETRY_SCOPE = ("fishnet_tpu/fleet", "fishnet_tpu/serve",
               "fishnet_tpu/client", "fishnet_tpu/cache",
               "tools/loadgen.py")

# awaited call tails that reach the network. Deliberately narrow:
# `acquire`/`go_multiple` are absent so the work queue's long-poll
# (client/queue.py) and the worker dispatch loop (client/workers.py)
# stay clean — their loops are exit-condition driven, not retry loops
_RETRY_NET_TAILS = ("open_connection", "open_unix_connection",
                    "readline", "readexactly", "readuntil", "drain",
                    "sendall", "urlopen", "getresponse",
                    "_round_trip", "_round_trip_inner", "_attempt",
                    "healthz")

# transport-level exception tails: catching one of these and looping
# again is a retry. Application errors (ApiError, ShuttingDown) are
# excluded — handlers for those encode protocol flow, not redial
_RETRY_EXC_TAILS = ("OSError", "ConnectionError", "ConnectionRefusedError",
                    "ConnectionResetError", "ConnectionAbortedError",
                    "BrokenPipeError", "TimeoutError",
                    "IncompleteReadError", "EngineError", "MemberFault",
                    "MemberBusy")

# for-loop iterables that never run dry
_RETRY_INFINITE_ITERS = ("count", "cycle", "repeat")

# the scheduler loops: blocking host syncs here stall the segment
# pipeline — engine/tpu.py holds the LaneScheduler, ops/search.py the
# stream/batch segment loops (both dispatch the sharded mesh callables)
HOST_SYNC_SCOPE = (
    "fishnet_tpu/engine/tpu.py",
    "fishnet_tpu/ops/search.py",
)

# the session journal lives in the supervisor; its single-writer
# invariant is what lets the recovery ladder trust exactly-once contents
JOURNAL_SCOPE = ("fishnet_tpu/engine/supervisor.py",)
_JOURNAL_ATTRS = ("_journal", "_journal_expect")
_JOURNAL_WRITERS = ("_journal_record", "_journal_reset", "__init__")
_MUT_METHODS = ("update", "pop", "clear", "setdefault", "popitem",
                "add", "discard", "remove")

# calls whose results are device arrays (or tuples of them); a local
# `dispatch`/`flush_adm` closure wrapping the segment jit counts too,
# as do the shard_map'd mesh callables (parallel/mesh.py) the sharded
# scheduler drives
_DEVICE_PRODUCERS = ("_run_segment_jit", "_init_state_jit",
                     "_merge_lanes_jit", "refill_lanes", "extract_results",
                     "dispatch", "flush_adm",
                     "run_segment_sharded", "refill_lanes_sharded")

# attribute calls that block the caller until a peer acts
_WAITING_ATTRS = ("join", "get", "wait", "recv")

# calls that block; write_frame is excluded deliberately — host.py's
# `with wlock: write_frame(...)` is the intended frame-stream serializer
_BLOCKING_IN_LOCK = ("join", "get", "wait", "recv", "sleep", "read_frame",
                     "acquire")

_BROAD = ("Exception", "BaseException")

_LOG_ATTRS = ("debug", "info", "warn", "warning", "error", "exception",
              "log", "headline", "progress")


def _parents(tree: ast.AST) -> dict:
    out = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            out[child] = node
    return out


def _inside_wait_for(node: ast.AST, parents: dict) -> bool:
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.Call) and \
                dotted(cur.func).split(".")[-1] == "wait_for":
            return True
        cur = parents.get(cur)
    return False


def _handler_type_names(handler: ast.ExceptHandler) -> List[str]:
    t = handler.type
    if t is None:
        return []
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    return [dotted(e).split(".")[-1] for e in elts]


def _body_raises(body: List[ast.stmt]) -> bool:
    return any(isinstance(n, ast.Raise) for n in ast.walk(ast.Module(
        body=body, type_ignores=[])))


def _body_logs(body: List[ast.stmt]) -> bool:
    for stmt in body:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            target = dotted(node.func)
            tail = target.split(".")[-1]
            if tail in _LOG_ATTRS or target in ("print", "log"):
                return True
    return False


def _body_trivial(body: List[ast.stmt]) -> bool:
    """pass/continue/break/`return <constant>`/docstring only — the
    handler observably does nothing with the failure."""
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Return) and (
            stmt.value is None or isinstance(stmt.value, ast.Constant)
        ):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue
        return False
    return True


def _assign_targets(node: ast.Assign) -> List[str]:
    out: List[str] = []
    for t in node.targets:
        elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
        for e in elts:
            if isinstance(e, ast.Name):
                out.append(e.id)
    return out


def _sync_sink(call: ast.Call, device: set) -> str:
    """Name of the device-resident value this call blocks on, or ''."""
    target = dotted(call.func)
    tail = target.split(".")[-1]
    arg = call.args[0] if call.args else None
    if target == "int" or tail in ("asarray", "device_get",
                                   "block_until_ready"):
        if isinstance(arg, ast.Name) and arg.id in device:
            return arg.id
    # method form: state.block_until_ready()
    if isinstance(call.func, ast.Attribute) and \
            call.func.attr == "block_until_ready" and \
            isinstance(call.func.value, ast.Name) and \
            call.func.value.id in device:
        return call.func.value.id
    return ""


def _check_host_sync(src, findings: List[Finding]) -> None:
    """Forward flow per function: names fed from the segment-dispatch
    jits are device-resident until rebound; materializing one inside a
    `while` loop other than via SyncStats.fetch is a finding."""
    parents = _parents(src.tree)

    def in_while(node: ast.AST) -> bool:
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.While):
                return True
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False
            cur = parents.get(cur)
        return False

    for fn in ast.walk(src.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        device: set = set()
        stmts = sorted(
            (n for n in ast.walk(fn)
             if isinstance(n, (ast.Assign, ast.Expr, ast.AugAssign))),
            key=lambda n: (n.lineno, n.col_offset),
        )
        for stmt in stmts:
            # sinks first: the RHS evaluates before the rebind
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call) and in_while(node):
                    name = _sync_sink(node, device)
                    if name:
                        findings.append(src.finding(
                            "conc-host-sync", node,
                            f"blocking host sync on device value "
                            f"'{name}' inside the scheduler loop; route "
                            "it through SyncStats.fetch so the transfer "
                            "is counted and the blocked time measured",
                        ))
            if not isinstance(stmt, ast.Assign):
                continue
            val = stmt.value
            is_device = False
            if isinstance(val, ast.Call):
                tail = dotted(val.func).split(".")[-1]
                is_device = tail in _DEVICE_PRODUCERS
            elif isinstance(val, ast.Name):
                is_device = val.id in device
            elif isinstance(val, ast.Subscript) and \
                    isinstance(val.value, ast.Name):
                # tt = pend[1]: slicing a device tuple stays on device
                is_device = val.value.id in device
            for name in _assign_targets(stmt):
                if is_device:
                    device.add(name)
                else:
                    device.discard(name)


def _journal_attr(node: ast.AST) -> str:
    """'_journal'/'_journal_expect' if node is (a subscript of) that
    attribute on self, else ''."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and node.attr in _JOURNAL_ATTRS and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return ""


def _check_journal_writer(src, findings: List[Finding]) -> None:
    """Single-writer invariant for the supervisor's session journal:
    any rebind, item write, delete, or mutating method call on
    self._journal / self._journal_expect outside the sanctioned delivery
    path is a finding."""
    parents = _parents(src.tree)

    def enclosing_fn(node: ast.AST) -> str:
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur.name
            cur = parents.get(cur)
        return ""

    for node in ast.walk(src.tree):
        name = ""
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                name = name or _journal_attr(t)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                name = name or _journal_attr(t)
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUT_METHODS:
            name = _journal_attr(node.func.value)
        if name and enclosing_fn(node) not in _JOURNAL_WRITERS:
            findings.append(src.finding(
                "conc-journal-writer", node,
                f"self.{name} mutated outside the supervisor's delivery "
                "path; the session journal is single-writer so the "
                "recovery ladder can trust exactly-once contents — "
                "route the write through _journal_record/_journal_reset",
            ))


def _check_sock_in_loop(src, findings: List[Finding]) -> None:
    """Blocking socket/IO calls inside an `async def`: the serving
    package's handlers all share one event loop, so a single blocking
    call freezes every connection. Sync helpers nested inside the async
    function are skipped — they run under to_thread/run_in_executor by
    construction (that's the sanctioned escape hatch)."""

    def async_body_calls(fn: ast.AsyncFunctionDef):
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # sync helper / inner coroutine (walked on its own)
            if isinstance(node, ast.Call):
                yield node
            stack.extend(ast.iter_child_nodes(node))

    for fn in ast.walk(src.tree):
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        for call in async_body_calls(fn):
            target = dotted(call.func)
            tail = target.split(".")[-1]
            if target in _BLOCKING_IN_LOOP_EXACT or \
                    tail in _BLOCKING_IN_LOOP_TAILS:
                findings.append(src.finding(
                    "conc-sock-in-loop", call,
                    f"blocking call {target}() inside an async handler "
                    "stalls the shared event loop — every tenant freezes "
                    "together; use asyncio streams / asyncio.sleep, or "
                    "push it through run_in_executor",
                ))


def _loop_unbounded(loop: ast.AST) -> bool:
    """True for loops with no intrinsic iteration cap: `while True`
    (or any constant-true test) and `for _ in itertools.count()`-style
    infinite iterables. A `while` over a real condition or a `for`
    over range()/a collection bounds itself."""
    if isinstance(loop, ast.While):
        return isinstance(loop.test, ast.Constant) and bool(loop.test.value)
    if isinstance(loop, ast.For):
        it = loop.iter
        return isinstance(it, ast.Call) and \
            dotted(it.func).split(".")[-1] in _RETRY_INFINITE_ITERS
    return False


def _walk_loop_body(loop: ast.AST):
    """Walk a loop body, skipping nested function defs (their loops are
    judged on their own) but descending into nested loops/try/if."""
    stack = list(loop.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _deadline_guarded(loop: ast.AST) -> bool:
    """A loop escapes the retry rule if its body carries a deadline
    guard: an `if` whose test consults a deadline/monotonic clock and
    whose body leaves the loop (break/return/raise)."""
    for node in _walk_loop_body(loop):
        if not isinstance(node, ast.If):
            continue
        mentions_clock = False
        for sub in ast.walk(node.test):
            name = ""
            if isinstance(sub, ast.Name):
                name = sub.id
            elif isinstance(sub, ast.Attribute):
                name = sub.attr
            low = name.lower()
            if "deadline" in low or "monotonic" in low or "slack" in low:
                mentions_clock = True
                break
        if not mentions_clock:
            continue
        for sub in ast.walk(ast.Module(body=node.body, type_ignores=[])):
            if isinstance(sub, (ast.Break, ast.Return, ast.Raise)):
                return True
    return False


def _handler_reiterates(handler: ast.ExceptHandler) -> bool:
    """A handler permits another lap unless its last statement
    unconditionally leaves the loop."""
    if not handler.body:
        return True
    return not isinstance(handler.body[-1], (ast.Raise, ast.Break,
                                             ast.Return))


def _check_unbounded_retry(src, findings: List[Finding]) -> None:
    """Unbounded retry around an awaited network call: a `while True`
    (or infinite `for`) whose try-body awaits the wire and whose
    handler catches a transport fault back into the next iteration.
    Against a dead peer this coroutine spins forever — cap it with
    `for attempt in range(N)` or a deadline check that breaks/raises
    (fleet/remote.py's in-dispatch retry is the canonical shape)."""
    for loop in ast.walk(src.tree):
        if not isinstance(loop, (ast.While, ast.For)):
            continue
        if not _loop_unbounded(loop) or _deadline_guarded(loop):
            continue
        for node in _walk_loop_body(loop):
            if not isinstance(node, ast.Try):
                continue
            awaits_net = any(
                isinstance(sub, ast.Await) and
                isinstance(sub.value, ast.Call) and
                dotted(sub.value.func).split(".")[-1] in _RETRY_NET_TAILS
                for stmt in node.body for sub in ast.walk(stmt)
            )
            if not awaits_net:
                continue
            retries = next(
                (h for h in node.handlers
                 if (h.type is None or
                     any(n in _RETRY_EXC_TAILS
                         for n in _handler_type_names(h))) and
                 _handler_reiterates(h)),
                None)
            if retries is None:
                continue
            findings.append(src.finding(
                "conc-unbounded-retry", retries,
                "transport fault caught back into an unbounded loop "
                "around an awaited network call; a dead peer spins "
                "this retry forever — bound it with an attempt cap "
                "(for attempt in range(N)) or a deadline guard that "
                "breaks/raises",
            ))


@register_family("concurrency")
def check_concurrency(project: Project) -> List[Finding]:
    findings: List[Finding] = []

    for src in project.in_dirs(*HOST_SYNC_SCOPE):
        _check_host_sync(src, findings)

    for src in project.in_dirs(*JOURNAL_SCOPE):
        _check_journal_writer(src, findings)

    for src in project.in_dirs(*SERVE_ASYNC_SCOPE):
        _check_sock_in_loop(src, findings)

    for src in project.in_dirs(*RETRY_SCOPE):
        _check_unbounded_retry(src, findings)

    for src in project.in_dirs(*BLOCK_SCOPE):
        parents = _parents(src.tree)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            attr = node.func.attr

            if attr in _WAITING_ATTRS and not node.args and \
                    not any(kw.arg == "timeout" for kw in node.keywords) and \
                    not _inside_wait_for(node, parents):
                findings.append(src.finding(
                    "conc-no-timeout", node,
                    f".{attr}() with no timeout blocks forever if the "
                    "peer is wedged; pass timeout= or wrap in "
                    "asyncio.wait_for",
                ))

        # blocking calls under a held (sync) lock; async locks are
        # legitimately held across awaits, so only ast.With is scanned
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.With):
                continue
            held_lock = any(
                "lock" in dotted(item.context_expr.func
                                 if isinstance(item.context_expr, ast.Call)
                                 else item.context_expr).lower()
                for item in node.items
            )
            if not held_lock:
                continue
            for sub in ast.walk(ast.Module(body=node.body, type_ignores=[])):
                if isinstance(sub, ast.Call):
                    tail = dotted(sub.func).split(".")[-1]
                    if tail in _BLOCKING_IN_LOCK:
                        findings.append(src.finding(
                            "conc-block-in-lock", sub,
                            f"{tail}() while holding a lock; every other "
                            "waiter stalls behind a wedged peer — move "
                            "the blocking call outside the critical "
                            "section",
                        ))

    for src in project.in_dirs(*EXCEPT_SCOPE):
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            names = _handler_type_names(node)
            if node.type is None:
                findings.append(src.finding(
                    "conc-bare-except", node,
                    "bare except also catches KeyboardInterrupt and "
                    "SystemExit; catch Exception (or narrower)",
                ))
            if "BaseException" in names and not _body_raises(node.body):
                findings.append(src.finding(
                    "conc-swallow-base", node,
                    "except BaseException without re-raise swallows "
                    "KeyboardInterrupt/SystemExit; re-raise or narrow",
                ))
            broad = node.type is None or any(n in _BROAD for n in names)
            if broad and _body_trivial(node.body) and \
                    not _body_logs(node.body):
                findings.append(src.finding(
                    "conc-silent-except", node,
                    "broad exception handler that neither logs nor "
                    "raises; failures vanish without a trace — log the "
                    "exception or narrow the type",
                ))

    return findings
