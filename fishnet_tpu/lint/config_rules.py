"""Config-coherence rules: the FISHNET_TPU_* env-var registry contract.

`fishnet_tpu/utils/settings.py` is the single source of truth for every
FISHNET_TPU_* environment variable. These rules keep the rest of the
codebase honest about it:

  config-env-read          a FISHNET_TPU_* name read directly from
                           os.environ / os.getenv outside settings.py —
                           use the typed accessors instead
  config-env-write         a FISHNET_TPU_* name written to os.environ
                           outside tests/, tools/, bench.py (production
                           code must not mutate its own config)
  config-env-unregistered  a FISHNET_TPU_* name used anywhere (accessor
                           arg, environ access, subscript key) that has
                           no registry entry
  config-registry-literal  the SETTINGS tuple contains a non-literal
                           entry, so the registry can't be extracted
                           statically
  config-doc-stale         docs/config.md does not match the table
                           rendered from the registry (regenerate with
                           `python -m fishnet_tpu.utils.settings`)
  config-engine-wire       engine/supervisor.py no longer applies
                           settings.engine_env() on spawn, stranding
                           engine-affecting vars on the parent side

The registry is extracted by AST from the scanned project's settings.py
(never imported), so fixture projects in the lint's own tests can carry
their own mini-registry.
"""
from __future__ import annotations

import ast
import re
from typing import List, Optional, Tuple

from .core import (
    Finding,
    Project,
    SourceFile,
    call_name,
    dotted,
    register_family,
    str_const,
)

PREFIX = "FISHNET_TPU_"
SETTINGS_REL = "fishnet_tpu/utils/settings.py"
SUPERVISOR_REL = "fishnet_tpu/engine/supervisor.py"
CONFIG_MD_REL = "docs/config.md"

# locations where writing FISHNET_TPU_* into os.environ is legitimate
# (test setup, one-off tools, the bench driver building child envs)
_WRITE_OK_PREFIXES = ("tests/", "tools/")
_WRITE_OK_FILES = ("bench.py", "__graft_entry__.py")

# typed accessors on the registry; the distinctive ones are also matched
# bare (imported names), the generic ones only as settings.<name>
_ACCESSORS = ("raw", "get_bool", "get_int", "get_str", "get_csv_int",
              "is_set", "lookup")
_DISTINCTIVE = ("get_bool", "get_int", "get_str", "get_csv_int", "is_set")

_NAME_RE = re.compile(r"^FISHNET_TPU_[A-Z0-9_]+$")


def extract_registry(
    src: SourceFile,
) -> Tuple[Optional[List[tuple]], List[Finding]]:
    """AST-extract (name, kind, default, doc, engine) rows from the
    literal SETTINGS tuple. Returns (rows, findings); rows is None when
    no SETTINGS assignment exists, and findings carry any non-literal
    entries (which also abort extraction)."""
    value = None
    for node in ast.walk(src.tree):
        if isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name) and \
                node.target.id == "SETTINGS":
            value = node.value
        elif isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "SETTINGS"
            for t in node.targets
        ):
            value = node.value
    if value is None:
        return None, []

    bad = src.finding(
        "config-registry-literal", value,
        "SETTINGS must be a tuple of Setting(...) calls with literal "
        "string/bool arguments — the lint extracts it without importing",
    )
    if not isinstance(value, ast.Tuple):
        return None, [bad]

    rows: List[tuple] = []
    for elt in value.elts:
        if not (isinstance(elt, ast.Call)
                and call_name(elt).split(".")[-1] == "Setting"):
            return None, [bad]
        kw = {k.arg: k.value for k in elt.keywords if k.arg}
        name = str_const(kw.get("name", ast.Pass()))
        kind = str_const(kw.get("kind", ast.Pass()))
        default = str_const(kw.get("default", ast.Pass()))
        doc = str_const(kw.get("doc", ast.Pass()))
        engine = False
        if "engine" in kw:
            e = kw["engine"]
            if not (isinstance(e, ast.Constant) and isinstance(e.value, bool)):
                return None, [bad]
            engine = e.value
        if None in (name, kind, default, doc):
            return None, [bad]
        rows.append((name, kind, default, doc, engine))
    return rows, []


def _literal_env_names(node: ast.Call) -> List[Tuple[ast.AST, str]]:
    out = []
    for arg in node.args[:1]:
        s = str_const(arg)
        if s is not None and s.startswith(PREFIX):
            out.append((arg, s))
    return out


@register_family("config")
def check_config_coherence(project: Project) -> List[Finding]:
    findings: List[Finding] = []

    settings_src = project.file(SETTINGS_REL)
    registered: Optional[set] = None
    rows: Optional[List[tuple]] = None
    if settings_src is not None:
        rows, reg_findings = extract_registry(settings_src)
        findings.extend(reg_findings)
        if rows is not None:
            registered = {r[0] for r in rows}

    def check_registered(src: SourceFile, node: ast.AST, name: str) -> None:
        if registered is not None and _NAME_RE.match(name) and \
                name not in registered:
            findings.append(src.finding(
                "config-env-unregistered", node,
                f"{name} is not registered in {SETTINGS_REL}; add a "
                "Setting entry (and regenerate docs/config.md)",
            ))

    for src in project.files:
        in_settings = src.rel == SETTINGS_REL
        write_ok = (
            src.rel.startswith(_WRITE_OK_PREFIXES)
            or src.rel in _WRITE_OK_FILES
        )
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                target = call_name(node)
                tail = target.split(".")[-1]

                is_environ_call = (
                    target.endswith("os.environ.get")
                    or target == "environ.get"
                    or target.endswith("os.getenv")
                    or target == "getenv"
                )
                is_environ_write_call = (
                    target.endswith("environ.setdefault")
                    or target.endswith("environ.pop")
                )
                is_accessor = (
                    (target.startswith("settings.") and tail in _ACCESSORS)
                    or (target in _DISTINCTIVE)
                )

                if is_environ_call or is_environ_write_call or is_accessor:
                    for arg, name in _literal_env_names(node):
                        check_registered(src, arg, name)
                        if in_settings:
                            continue
                        if is_environ_call:
                            findings.append(src.finding(
                                "config-env-read", node,
                                f"direct environment read of {name}; go "
                                "through fishnet_tpu.utils.settings "
                                "(typed accessors, normalized bool "
                                "grammar, documented defaults)",
                            ))
                        elif is_environ_write_call and not write_ok:
                            findings.append(src.finding(
                                "config-env-write", node,
                                f"production code mutates {name} in "
                                "os.environ; config writes belong in "
                                "tests/, tools/, or bench.py",
                            ))

            elif isinstance(node, ast.Subscript):
                name = str_const(node.slice)
                if name is None or not name.startswith(PREFIX):
                    continue
                base = dotted(node.value)
                check_registered(src, node.slice, name)
                if base.endswith("os.environ") or base == "environ":
                    if in_settings:
                        continue
                    if isinstance(node.ctx, ast.Load):
                        findings.append(src.finding(
                            "config-env-read", node,
                            f"direct environment read of {name}; go "
                            "through fishnet_tpu.utils.settings",
                        ))
                    elif not write_ok:
                        findings.append(src.finding(
                            "config-env-write", node,
                            f"production code mutates {name} in "
                            "os.environ; config writes belong in "
                            "tests/, tools/, or bench.py",
                        ))

            elif isinstance(node, ast.Compare):
                # `"FISHNET_TPU_X" in os.environ` is a read in disguise
                name = str_const(node.left)
                if name and name.startswith(PREFIX) and len(node.ops) == 1 \
                        and isinstance(node.ops[0], (ast.In, ast.NotIn)):
                    comp = dotted(node.comparators[0])
                    if comp.endswith("os.environ") or comp == "environ":
                        check_registered(src, node.left, name)
                        if not in_settings:
                            findings.append(src.finding(
                                "config-env-read", node,
                                f"membership test of {name} in os.environ; "
                                "use settings.is_set()",
                            ))

    # --- docs/config.md staleness -------------------------------------
    if rows is not None:
        from ..utils.settings import render_rows

        anchor = settings_src.tree
        doc_path = project.root / CONFIG_MD_REL
        expected = render_rows(rows)
        if not doc_path.is_file():
            findings.append(settings_src.finding(
                "config-doc-stale", anchor,
                f"{CONFIG_MD_REL} is missing; generate it with "
                "`python -m fishnet_tpu.utils.settings > docs/config.md`",
            ))
        elif doc_path.read_text(encoding="utf-8") != expected:
            findings.append(settings_src.finding(
                "config-doc-stale", anchor,
                f"{CONFIG_MD_REL} does not match the registry; regenerate "
                "with `python -m fishnet_tpu.utils.settings > "
                "docs/config.md`",
            ))

    # --- engine-affecting vars must be wired to the engine host -------
    supervisor = project.file(SUPERVISOR_REL)
    if supervisor is not None and registered is not None:
        wired = any(
            isinstance(n, ast.Call)
            and call_name(n).split(".")[-1] == "engine_env"
            for n in ast.walk(supervisor.tree)
        )
        if not wired:
            findings.append(supervisor.finding(
                "config-engine-wire", supervisor.tree,
                "the engine host spawn path no longer applies "
                "settings.engine_env(); engine-affecting FISHNET_TPU_* "
                "vars would strand on the parent side of a sanitized "
                "spawn",
            ))

    return findings
