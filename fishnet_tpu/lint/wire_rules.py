"""Wire-schema rules: dataclass fields vs serde keys.

The supervisor↔host pipe protocol and the lichess wire model both use
hand-written to/from JSON-dict converters over plain dataclasses
(client/wire.py, client/ipc.py). Nothing ties a dataclass field to its
serde key until a message round-trips at runtime — adding a field and
forgetting one side silently drops data. These rules diff the two sides
statically:

  wire-field-missing        a dataclass field is never attribute-read in
                            the pair's to-side functions (it won't be
                            serialized)
  wire-ctor-field-mismatch  a from-side constructor call passes a kwarg
                            that is not a field, or omits a field with
                            no default
  wire-key-asymmetry        the literal key sets emitted by the to-side
                            and consumed by the from-side differ

Pairs are declared explicitly below (a pair may union helper functions:
the work pair's from-side includes NodeLimit.from_json/Clock.from_json
because the keys they consume are emitted by work_to_json's nested
dicts). Dataclasses that carry a to_json/from_json method pair are also
auto-discovered. A to-side that emits non-literal dict keys (Score's
`{self.kind: self.value}`) opts its pair out of the key-asymmetry check
only.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .core import (
    Finding,
    Project,
    SourceFile,
    dotted,
    register_family,
    str_const,
)


@dataclass(frozen=True)
class SerdePair:
    name: str
    file: str
    to_fns: Tuple[str, ...]    # qualified: "work_to_json", "Score.to_json"
    from_fns: Tuple[str, ...]
    dataclasses: Tuple[str, ...]
    # "Class.field" names that legitimately do not travel on this wire
    # (e.g. PositionResponse.work rides in the surrounding frame)
    exempt: Tuple[str, ...] = ()


SERDE_PAIRS: Tuple[SerdePair, ...] = (
    SerdePair(
        name="work",
        file="fishnet_tpu/client/wire.py",
        to_fns=("work_to_json",),
        from_fns=("work_from_json", "NodeLimit.from_json", "Clock.from_json"),
        dataclasses=("AnalysisWork", "MoveWork", "NodeLimit", "Clock"),
    ),
    SerdePair(
        name="chunk",
        file="fishnet_tpu/client/ipc.py",
        to_fns=("chunk_to_wire",),
        from_fns=("chunk_from_wire",),
        dataclasses=("Chunk", "WorkPosition"),
        exempt=("WorkPosition.work",),  # rebuilt from the chunk's work
    ),
    SerdePair(
        name="response",
        file="fishnet_tpu/client/ipc.py",
        to_fns=("response_to_wire",),
        from_fns=("responses_from_wire",),
        dataclasses=("PositionResponse",),
        exempt=("PositionResponse.work",),  # travels in the frame header
    ),
    SerdePair(
        name="score",
        file="fishnet_tpu/client/wire.py",
        to_fns=("Score.to_json",),
        from_fns=("Score.from_json",),
        dataclasses=("Score",),
    ),
)

# files swept for auto-discovered to_json/from_json dataclass pairs
AUTO_FILES = (
    "fishnet_tpu/client/wire.py",
    "fishnet_tpu/client/ipc.py",
    "fishnet_tpu/engine/frames.py",
)


def _is_dataclass_def(node: ast.ClassDef) -> bool:
    for deco in node.decorator_list:
        target = deco.func if isinstance(deco, ast.Call) else deco
        if dotted(target).split(".")[-1] == "dataclass":
            return True
    return False


def _dataclass_fields(node: ast.ClassDef) -> List[Tuple[str, bool]]:
    """(field name, has_default) in declaration order; ClassVar and plain
    assignments (constants) are not fields."""
    out: List[Tuple[str, bool]] = []
    for stmt in node.body:
        if not (isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)):
            continue
        ann = stmt.annotation
        ann_name = dotted(ann) if not isinstance(ann, ast.Subscript) \
            else dotted(ann.value)
        if ann_name.split(".")[-1] == "ClassVar":
            continue
        out.append((stmt.target.id, stmt.value is not None))
    return out


def _index_file(src: SourceFile):
    """Qualified function map ('fn', 'Cls.fn') and dataclass defs."""
    fns: Dict[str, ast.AST] = {}
    classes: Dict[str, ast.ClassDef] = {}
    for node in src.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fns[node.name] = node
        elif isinstance(node, ast.ClassDef):
            classes[node.name] = node
            for stmt in node.body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fns[f"{node.name}.{stmt.name}"] = stmt
    return fns, classes


@dataclass
class _SideKeys:
    keys: Set[str] = field(default_factory=set)
    dynamic: bool = False  # non-literal dict key seen on the to-side


def _emitted_keys(fn_node: ast.AST) -> _SideKeys:
    out = _SideKeys()
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Dict):
            for key in node.keys:
                if key is None:  # **spread
                    out.dynamic = True
                    continue
                s = str_const(key)
                if s is None:
                    out.dynamic = True
                else:
                    out.keys.add(s)
        elif isinstance(node, ast.Subscript) and \
                isinstance(node.ctx, ast.Store):
            s = str_const(node.slice)
            if s is not None:
                out.keys.add(s)
    return out


def _consumed_keys(fn_node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
            s = str_const(node.slice)
            if s is not None:
                out.add(s)
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "get" and node.args:
            s = str_const(node.args[0])
            if s is not None:
                out.add(s)
        elif isinstance(node, ast.Compare) and len(node.ops) == 1 and \
                isinstance(node.ops[0], (ast.In, ast.NotIn)):
            s = str_const(node.left)
            if s is not None:
                out.add(s)
    return out


def _attr_reads(fn_nodes: List[ast.AST]) -> Set[str]:
    out: Set[str] = set()
    for fn_node in fn_nodes:
        for node in ast.walk(fn_node):
            if isinstance(node, ast.Attribute):
                out.add(node.attr)
    return out


def _discover_pairs(project: Project) -> List[SerdePair]:
    pairs = list(SERDE_PAIRS)
    covered = {(p.file, cls) for p in pairs for cls in p.dataclasses}
    for rel in AUTO_FILES:
        src = project.file(rel)
        if src is None:
            continue
        _, classes = _index_file(src)
        for cls_name, cls_node in classes.items():
            if (rel, cls_name) in covered or not _is_dataclass_def(cls_node):
                continue
            methods = {
                stmt.name for stmt in cls_node.body
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            if "to_json" in methods and "from_json" in methods:
                pairs.append(SerdePair(
                    name=cls_name.lower(),
                    file=rel,
                    to_fns=(f"{cls_name}.to_json",),
                    from_fns=(f"{cls_name}.from_json",),
                    dataclasses=(cls_name,),
                ))
    return pairs


@register_family("wire")
def check_wire_schema(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for pair in _discover_pairs(project):
        src = project.file(pair.file)
        if src is None:
            continue
        fns, classes = _index_file(src)

        to_nodes = [fns[n] for n in pair.to_fns if n in fns]
        from_nodes = [fns[n] for n in pair.from_fns if n in fns]
        if not to_nodes or not from_nodes:
            continue  # one-sided types (e.g. acquire body) are out of scope

        # W001: every field must be attribute-read somewhere on the to-side
        reads = _attr_reads(to_nodes)
        for cls_name in pair.dataclasses:
            cls_node = classes.get(cls_name)
            if cls_node is None:
                continue
            for fname, _ in _dataclass_fields(cls_node):
                if fname in reads or f"{cls_name}.{fname}" in pair.exempt:
                    continue
                findings.append(src.finding(
                    "wire-field-missing", cls_node,
                    f"{cls_name}.{fname} is never read by "
                    f"{'/'.join(pair.to_fns)}; the field is silently "
                    "dropped on serialization",
                ))

        # W002: from-side constructor calls vs the field list
        for cls_name in pair.dataclasses:
            cls_node = classes.get(cls_name)
            if cls_node is None:
                continue
            fields = _dataclass_fields(cls_node)
            fieldset = {f for f, _ in fields}
            for fn_node in from_nodes:
                for node in ast.walk(fn_node):
                    if not (isinstance(node, ast.Call)
                            and dotted(node.func) == cls_name):
                        continue
                    kwargs = {k.arg for k in node.keywords if k.arg}
                    has_splat = any(k.arg is None for k in node.keywords)
                    for kw in sorted(kwargs - fieldset):
                        findings.append(src.finding(
                            "wire-ctor-field-mismatch", node,
                            f"{cls_name}(... {kw}=...) passes a kwarg "
                            "that is not a dataclass field",
                        ))
                    if has_splat:
                        continue
                    positional = {f for f, _ in fields[:len(node.args)]}
                    for fname, has_default in fields:
                        if has_default or fname in kwargs \
                                or fname in positional \
                                or f"{cls_name}.{fname}" in pair.exempt:
                            continue
                        findings.append(src.finding(
                            "wire-ctor-field-mismatch", node,
                            f"{cls_name}(...) omits required field "
                            f"{fname!r}",
                        ))

        # W003: literal key symmetry between the two sides
        emitted = _SideKeys()
        for fn_node in to_nodes:
            side = _emitted_keys(fn_node)
            emitted.keys |= side.keys
            emitted.dynamic = emitted.dynamic or side.dynamic
        if emitted.dynamic:
            continue  # dynamic keys (Score) can't be diffed statically
        consumed: Set[str] = set()
        for fn_node in from_nodes:
            consumed |= _consumed_keys(fn_node)
        for key in sorted(emitted.keys - consumed):
            findings.append(src.finding(
                "wire-key-asymmetry", to_nodes[0],
                f"serde pair {pair.name!r}: key {key!r} is emitted by "
                f"{'/'.join(pair.to_fns)} but never consumed by "
                f"{'/'.join(pair.from_fns)}",
            ))
        for key in sorted(consumed - emitted.keys):
            findings.append(src.finding(
                "wire-key-asymmetry", from_nodes[0],
                f"serde pair {pair.name!r}: key {key!r} is consumed by "
                f"{'/'.join(pair.from_fns)} but never emitted by "
                f"{'/'.join(pair.to_fns)}",
            ))
    return findings
