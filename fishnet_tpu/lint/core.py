"""fishnet-lint core: findings, suppressions, baseline, project model.

The suite is pure-stdlib AST analysis (no imports of the code under
scan, no JAX) so it runs identically in a bare CI job, a pre-commit
hook, and the test suite. Rules are project-invariant checks tailored
to this codebase — see docs/lint.md for the rule catalogue.

Suppression syntax (same line or a comment-only line directly above):

    x = risky()  # fishnet-lint: disable=conc-no-timeout
    # fishnet-lint: disable=trace-int-dtype,trace-py-branch
    y = jnp.arange(8)

Baseline: a checked-in JSON file of finding fingerprints (rule + file +
stripped source line — line numbers deliberately excluded so unrelated
edits don't invalidate it). Baselined findings are reported as such and
do not fail the gate; `--write-baseline` regenerates the file.
"""
from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set

_SUPPRESS_RE = re.compile(r"#\s*fishnet-lint:\s*disable=([A-Za-z0-9_,\- ]+)")


@dataclass
class Finding:
    rule: str
    path: str  # project-root-relative, forward slashes
    line: int
    col: int
    message: str
    source_line: str = ""
    baselined: bool = False

    def fingerprint(self) -> str:
        return f"{self.rule}::{self.path}::{self.source_line.strip()}"

    def format_text(self) -> str:
        tag = " [baselined]" if self.baselined else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule}{tag}: {self.message}"

    def format_github(self) -> str:
        # GitHub annotation message field must be single-line
        msg = self.message.replace("\n", " ")
        return (
            f"::error file={self.path},line={self.line},col={self.col},"
            f"title=fishnet-lint {self.rule}::{msg}"
        )


class SourceFile:
    """One parsed python file plus its suppression map."""

    def __init__(self, root: Path, abspath: Path) -> None:
        self.abspath = abspath
        self.rel = abspath.relative_to(root).as_posix()
        self.text = abspath.read_text(encoding="utf-8")
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=self.rel)
        self.suppressions = self._parse_suppressions()

    def _parse_suppressions(self) -> Dict[int, Set[str]]:
        out: Dict[int, Set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            target = i
            if line.lstrip().startswith("#"):
                target = i + 1  # comment-only line governs the next line
            out.setdefault(target, set()).update(rules)
        return out

    def suppressed(self, rule: str, line: int) -> bool:
        rules = self.suppressions.get(line)
        return bool(rules) and (rule in rules or "all" in rules)

    def source_at(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0) + 1
        return Finding(
            rule=rule, path=self.rel, line=line, col=col,
            message=message, source_line=self.source_at(line),
        )


# default scan set: the package, its drivers, and the test tree
SCAN_GLOBS = (
    "fishnet_tpu/**/*.py",
    "tools/*.py",
    "tests/*.py",
    "bench.py",
    "__graft_entry__.py",
)


class Project:
    """The parsed file set of one repository (or test fixture) root."""

    def __init__(self, root: Path, files: Sequence[SourceFile]) -> None:
        self.root = Path(root)
        self.files = list(files)
        self._by_rel = {f.rel: f for f in self.files}

    @classmethod
    def load(cls, root: Path, globs: Iterable[str] = SCAN_GLOBS) -> "Project":
        root = Path(root).resolve()
        seen = set()
        files: List[SourceFile] = []
        errors: List[str] = []
        for pattern in globs:
            for p in sorted(root.glob(pattern)):
                if not p.is_file() or p in seen or "__pycache__" in p.parts:
                    continue
                seen.add(p)
                try:
                    files.append(SourceFile(root, p))
                except SyntaxError as e:
                    errors.append(f"{p}: {e}")
        if errors:
            raise SyntaxError("unparseable files:\n" + "\n".join(errors))
        return cls(root, files)

    def file(self, rel: str) -> Optional[SourceFile]:
        return self._by_rel.get(rel)

    def in_dirs(self, *prefixes: str) -> List[SourceFile]:
        return [
            f for f in self.files
            if any(f.rel == p or f.rel.startswith(p.rstrip("/") + "/")
                   for p in prefixes)
        ]


# ------------------------------------------------------------------ rules

# a rule family is a callable Project -> List[Finding]; registration
# keeps (family name, callable) so the CLI can filter/summarize
_FAMILIES: List[tuple] = []


def register_family(name: str) -> Callable:
    def deco(fn: Callable) -> Callable:
        _FAMILIES.append((name, fn))
        return fn

    return deco


def families() -> List[tuple]:
    return list(_FAMILIES)


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)
    stale_baseline: List[str] = field(default_factory=list)

    @property
    def active(self) -> List[Finding]:
        return [f for f in self.findings if not f.baselined]

    @property
    def failed(self) -> bool:
        return bool(self.active)


def run_lint(
    project: Project,
    baseline: Optional[Sequence[str]] = None,
    only_families: Optional[Set[str]] = None,
) -> LintResult:
    # rule modules self-register on import
    from . import aot_rules  # noqa: F401
    from . import cache_rules  # noqa: F401
    from . import concurrency_rules  # noqa: F401
    from . import config_rules  # noqa: F401
    from . import dataflow_rules  # noqa: F401
    from . import mesh_rules  # noqa: F401
    from . import obs_rules  # noqa: F401
    from . import trace_rules  # noqa: F401
    from . import wire_rules  # noqa: F401

    findings: List[Finding] = []
    for name, fn in families():
        if only_families and name not in only_families:
            continue
        findings.extend(fn(project))

    # drop inline-suppressed findings
    kept: List[Finding] = []
    for f in findings:
        src = project.file(f.path)
        if src is not None and src.suppressed(f.rule, f.line):
            continue
        kept.append(f)

    # consume baseline entries (multiset: one entry absolves one finding)
    remaining: Dict[str, int] = {}
    for entry in baseline or ():
        remaining[entry] = remaining.get(entry, 0) + 1
    for f in kept:
        fp = f.fingerprint()
        if remaining.get(fp, 0) > 0:
            remaining[fp] -= 1
            f.baselined = True

    kept.sort(key=lambda f: (f.path, f.line, f.rule))
    stale = [e for e, n in remaining.items() if n > 0 for _ in range(n)]
    return LintResult(findings=kept, stale_baseline=sorted(stale))


# --------------------------------------------------------------- baseline


def load_baseline(path: Path) -> List[str]:
    data = json.loads(path.read_text(encoding="utf-8"))
    if not isinstance(data, dict) or data.get("version") != 1:
        raise ValueError(f"{path}: unsupported baseline format")
    entries = data.get("entries", [])
    if not all(isinstance(e, str) for e in entries):
        raise ValueError(f"{path}: baseline entries must be strings")
    return list(entries)


def dump_baseline(findings: Iterable[Finding]) -> str:
    entries = sorted(f.fingerprint() for f in findings)
    return json.dumps({"version": 1, "entries": entries}, indent=2) + "\n"


# ------------------------------------------------------------ AST helpers


def call_name(node: ast.Call) -> str:
    """Dotted name of a call target: 'os.environ.get', 'jnp.arange',
    'foo'. Empty string for computed targets."""
    return dotted(node.func)


def dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def has_kwarg(call: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in call.keywords)
