"""Benchmark: batched alpha-beta + NNUE nodes/sec on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The north-star metric (BASELINE.md) is nodes/sec/chip on a 256-position
batch. vs_baseline divides by the reference client's own per-core NPS
scheduling prior (400 knps, reference: src/stats.rs:203-214) × host cores —
the documented proxy for "Stockfish-AVX2 on the same host" since this image
bundles no Stockfish binary to measure directly.

Hang-proofing (round-2 lesson: a device-side hang starved the in-process
ramp and the artifact recorded nothing): every stage runs in its OWN
subprocess with its own wall-clock timeout, and streams timestamped
phase heartbeats (compile_start / compile_done / exec segments) to stderr
so a recorded tail localizes any hang to compile vs run. A stage that
dies never takes the harness down; the final JSON always prints.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# (lanes, depth) ramp: known-good shapes first (docs/tpu-hang.md bisection),
# so small real numbers are on record before the north-star shape — which is
# attempted last because a hang there can wedge the tunnel for later stages.
# (64,3)/(128,3)/(256,3) middle shapes added in round 5 (VERDICT r4 weak #2:
# the round-4 ramp had no middle shape, so when (256,4) died the recorded
# headline under-reported the same session's matrix numbers by ~3x)
STAGES = [(8, 2), (64, 2), (64, 3), (128, 3), (256, 3), (256, 4),
          (512, 3), (1024, 3)]

# Device stages run with FISHNET_TPU_SELECT_UPDATES=1 FIRST: the round-3
# bisection (docs/tpu-hang.md) pinned the B>=16/max_ply>=4 hang/worker-crash
# on a suspected miscompiled scatter, and the one-hot select mode is the
# CPU-proven candidate fix. A stage that dies in select mode is retried once
# in the default scatter mode, so the artifact records which compile path
# (if any) works on the hardware.
SELECT_FIRST = os.environ.get("BENCH_SELECT_FIRST", "1") != "0"


def _hb(t0: float, msg: str) -> None:
    # shared phase-heartbeat formatter: the engine supervisor's child host
    # (engine/host.py) emits the same scheme over its pipe protocol
    from fishnet_tpu.utils.heartbeat import stamp

    stamp(t0, msg, tag="bench")


# BASELINE.md benchmark-config position sets
FENS_STANDARD = [
    "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
    "r1bqkbnr/pppp1ppp/2n5/4p3/2B1P3/5N2/PPPP1PPP/RNBQK2R b KQkq - 3 3",
    "r3k2r/p1ppqpb1/bn2pnp1/3PN3/1p2P3/2N2Q1p/PPPBBPPP/R3K2R w KQkq - 0 1",
    "rnbq1k1r/pp1Pbppp/2p5/8/2B5/8/PPP1NnPP/RNBQK2R w KQ - 1 8",
    "r4rk1/1pp1qppp/p1np1n2/2b1p1B1/2B1P1b1/P1NP1N2/1PP1QPPP/R4RK1 w - - 0 10",
    "8/2p5/3p4/KP5r/1R3p1k/8/4P1P1/8 w - - 0 1",
    "4k3/8/8/8/8/8/4P3/4K3 w - - 0 1",
    "6k1/5ppp/8/8/8/8/5PPP/3R2K1 w - - 0 1",
]
# Chess960 starting arrays (X-FEN; castling via rook files — the device
# castling rows store rook squares, so FRC is the same compiled program)
FENS_960 = [
    "bqnbrkrn/pppppppp/8/8/8/8/PPPPPPPP/BQNBRKRN w KQkq - 0 1",
    "nrbqkbrn/pppppppp/8/8/8/8/PPPPPPPP/NRBQKBRN w KQkq - 0 1",
    "rkbnnbqr/pppppppp/8/8/8/8/PPPPPPPP/RKBNNBQR w KQkq - 0 1",
    "qrknnrbb/pppppppp/8/8/8/8/PPPPPPPP/QRKNNRBB w KQkq - 0 1",
]
FENS_VARIANT = {
    "crazyhouse": [
        "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR[] w KQkq - 0 1",
        "rnb1kbnr/ppp1pppp/8/3p4/3P4/8/PPPqPPPP/RNBQKBNR[Pp] w KQkq - 0 4",
    ],
    "threeCheck": [
        "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
        "r1bqkbnr/pppp1ppp/2n5/4p3/2B1P3/5N2/PPPP1PPP/RNBQK2R b KQkq - 3 3",
    ],
}


def _roots_for(B: int, variant: str, fen_set: str):
    """B lane roots (+ multipv lane table when fen_set == 'multipv')."""
    from fishnet_tpu.chess import Position
    from fishnet_tpu.chess.variants import from_fen
    from fishnet_tpu.ops.board import from_position, stack_boards

    if fen_set == "960":
        fens = FENS_960
    elif fen_set == "variant":
        fens = FENS_VARIANT[variant]
    else:
        fens = FENS_STANDARD
    if variant == "standard":
        positions = [Position.from_fen(f) for f in fens]
    else:
        positions = [from_fen(f, variant) for f in fens]
    if fen_set == "multipv":
        # BASELINE config 3: every legal root move of every position
        # becomes a lane — the engine's multipv decomposition
        boards = []
        for p in positions:
            for m in p.legal_moves():
                boards.append(from_position(p.push(m)))
        boards = boards[:B]
        return stack_boards(boards + [boards[0]] * (B - len(boards)))
    return stack_boards(
        [from_position(positions[i % len(positions)]) for i in range(B)]
    )


def _all_boards_for(B: int, variant: str, fen_set: str):
    """The UNTRUNCATED workload for the refill comparison: every
    root-move board of the multipv decomposition (229 for the standard
    8-FEN set), or the fen set tiled to 2*B positions otherwise — more
    positions than lanes is the regime continuous refill exists for."""
    from fishnet_tpu.chess import Position
    from fishnet_tpu.chess.variants import from_fen
    from fishnet_tpu.ops.board import from_position, stack_boards

    if fen_set == "960":
        fens = FENS_960
    elif fen_set == "variant":
        fens = FENS_VARIANT[variant]
    else:
        fens = FENS_STANDARD
    if variant == "standard":
        positions = [Position.from_fen(f) for f in fens]
    else:
        positions = [from_fen(f, variant) for f in fens]
    if fen_set == "multipv":
        boards = []
        for p in positions:
            for m in p.legal_moves():
                boards.append(from_position(p.push(m)))
    else:
        boards = [
            from_position(positions[i % len(positions)])
            for i in range(2 * B)
        ]
    return stack_boards(boards), len(boards)


def _bench_refill(t0: float, params, B: int, depth: int, budget: int,
                  variant: str, fen_set: str, max_ply: int, tt,
                  stream: bool, mode: str, platform: str,
                  tt_log2: int, bench_dtype: str, mesh=None) -> None:
    """Refill A/B stage (ISSUE 4): positions_done_per_s over the SAME
    N-position workload at the SAME width B — chunk-serial width-B
    batches drained one after another (stream=False, the
    `_go_multiple_locked` regime) vs one full-width program whose DONE
    lanes are respliced with queued positions at segment boundaries
    (stream=True, ops/search.py search_stream). Occupancy counters land
    in the RESULT JSON either way.

    mesh (BENCH_MESH, round 10): both passes run sharded over the mesh
    devices — serial through search_batch_resumable(mesh=...), streamed
    through search_stream(mesh=...) with shard-local refill — and the
    stream summary grows per-shard mean live fractions."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fishnet_tpu.ops import search as S
    from fishnet_tpu.utils import settings

    seg = int(os.environ.get("BENCH_SEG", "1024"))
    roots, N = _all_boards_for(B, variant, fen_set)
    depth_all = np.full(N, depth, np.int32)
    budget_all = np.full(N, budget, np.int32)
    _hb(t0, f"refill stage: N={N} positions, width={B}, "
            f"mode={'stream' if stream else 'serial'}")

    def serial_pass(tt):
        """ceil(N/B) strictly-serial width-B dispatches; the last batch
        runs mostly padding — exactly the chunk-drain waste refill
        removes."""
        done = 0
        nodes = 0
        for lo in range(0, N, B):
            idx = np.arange(lo, min(lo + B, N))
            pad = np.concatenate([idx, np.full(B - idx.size, idx[0])])
            batch = jax.tree.map(lambda a: jnp.asarray(np.asarray(a)[pad]), roots)
            d_arr = np.where(np.arange(B) < idx.size, depth, 0)
            b_arr = np.where(np.arange(B) < idx.size, budget, 0)
            out = S.search_batch_resumable(
                params, batch,
                d_arr.astype(np.int32), b_arr.astype(np.int32),
                max_ply=max_ply, segment_steps=seg, tt=tt,
                variant=variant, mesh=mesh,
            )
            tt = out.pop("tt")
            jax.block_until_ready(out["nodes"])
            done += int(np.asarray(out["done"])[: idx.size].sum())
            nodes += int(np.asarray(out["nodes"])[: idx.size].sum())
        return done, nodes, tt, None

    def stream_pass(tt):
        out = S.search_stream(
            params, roots, depth_all, budget_all, max_ply=max_ply,
            width=B, segment_steps=seg, tt=tt, variant=variant,
            mesh=mesh,
        )
        jax.block_until_ready(out["nodes"])
        done = int(np.asarray(out["done"]).sum())
        nodes = int(np.asarray(out["nodes"]).sum())
        occ = out["occupancy"]
        lane_steps = sum(o["steps"] * B for o in occ) or 1
        live_steps = sum(o["steps"] * o["live"] for o in occ)
        host_ms = sum(o["host_ms"] for o in occ)
        device_ms = sum(o["device_ms"] for o in occ)
        summary = {
            "segments": len(occ),
            "refills": out["refills"],
            "mean_live_frac": round(live_steps / lane_steps, 4),
            # segment-pipeline A/B columns (round 8): the host/device
            # wall-clock split of every boundary interval and the
            # transfer count (utils/syncstats.py via search_stream)
            "host_ms": round(host_ms, 1),
            "device_ms": round(device_ms, 1),
            "boundary_share": round(
                host_ms / max(host_ms + device_ms, 1e-9), 4),
            "transfers": sum(o["transfers"] for o in occ),
            "pipeline": int(settings.get_bool("FISHNET_TPU_PIPELINE")),
        }
        if mesh is not None:
            # per-shard mean live fraction (shard_live columns from
            # search_stream's mesh occupancy rows): imbalance here means
            # the most-free-shard admission policy is not keeping up
            ndev = mesh.devices.size
            local = B // ndev
            denom = sum(o["steps"] * local for o in occ) or 1
            summary["ndev"] = ndev
            summary["shard_mean_live"] = [
                round(sum(o["steps"] * o["shard_live"][s] for o in occ)
                      / denom, 4)
                for s in range(ndev)
            ]
        return done, nodes, out["tt"], summary

    run = stream_pass if stream else serial_pass
    from fishnet_tpu.obs import trace

    refill_mode = "stream" if stream else "serial"
    _hb(t0, "exec_start warmup pass (compiles all programs)")
    with trace.span("bench.warmup", "bench", mode=refill_mode, B=B, N=N):
        done, nodes, tt, occ = run(tt)
    _hb(t0, f"exec_done warmup (done={done}/{N})")
    _hb(t0, "exec_start timed pass")
    t1 = time.perf_counter()
    with trace.span("bench.search", "bench", mode=refill_mode, B=B, N=N):
        done, nodes, tt, occ = run(tt)
    dt = time.perf_counter() - t1
    _hb(t0, f"exec_done timed: done={done}/{N}, {nodes:,} nodes in {dt:.2f}s")
    print(
        "RESULT "
        + json.dumps({
            "nps": nodes / dt,
            "B": B,
            "depth": depth,
            "nodes": nodes,
            "dt": dt,
            "platform": platform,
            "variant": variant,
            "fen_set": fen_set,
            "row_mode": mode,
            "max_ply": max_ply,
            "positions": N,
            "positions_done": done,
            "positions_done_per_s": round(done / dt, 1),
            "refill": "stream" if stream else "serial",
            "mesh": 0 if mesh is None else int(mesh.devices.size),
            "occupancy": occ,
            "net": os.environ.get("BENCH_NET", "random"),
            "dtype": bench_dtype or "f32",
            "tt_log2": tt_log2,
        }),
        flush=True,
    )
    rec = trace.RECORDER
    if rec is not None:
        path = rec.flight_dump(
            settings.get_str("FISHNET_TPU_TRACE_DIR"),
            f"bench-refill-{'stream' if stream else 'serial'}-b{B}",
        )
        _hb(t0, f"trace dumped to {path}")


def stage_main(B: int, depth: int, budget: int, variant: str = "standard",
               fen_set: str = "standard") -> None:
    """Child process: run one (B, depth) stage with phase heartbeats.

    On success prints exactly one stdout line: RESULT {json}."""
    from fishnet_tpu.obs import trace
    from fishnet_tpu.utils import settings

    # phase transitions go through the shared recorder (off unless
    # FISHNET_TPU_TRACE_DIR is set), so a bench run produces the same
    # Chrome-trace timeline as the engine — not just stderr stamps
    rec = trace.install_from_settings("bench")
    t0 = time.monotonic()
    mode = ("select" if settings.get_bool("FISHNET_TPU_SELECT_UPDATES")
            else "scatter")
    _hb(t0, f"stage B={B} depth={depth} variant={variant} set={fen_set} "
            f"row_mode={mode}: importing jax")
    with trace.span("bench.import_jax", "bench"):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from fishnet_tpu.utils import enable_compile_cache

        enable_compile_cache()
        platform = jax.default_backend()
    _hb(t0, f"devices={jax.devices()} platform={platform}")

    from fishnet_tpu.models import nnue
    from fishnet_tpu.ops import search as S

    roots = _roots_for(B, variant, fen_set)
    # stage knobs (inherited via env by the stage subprocess):
    #   BENCH_NET=default  → the packaged trained net (production weights)
    #   BENCH_DTYPE=bf16|int8 → quantized eval path (SURVEY §7.2)
    #   BENCH_MAX_PLY=N    → production stack height (default: depth+1 toy)
    bench_net = os.environ.get("BENCH_NET", "")
    if bench_net == "default":
        from fishnet_tpu.assets import load_default_params

        params = load_default_params("board768")
        if params is None:
            raise RuntimeError("packaged net missing")
    elif bench_net in ("", "random"):
        params = nnue.init_params(
            jax.random.PRNGKey(0), l1=64, feature_set="board768"
        )
    else:
        # a typo'd net name must not record a random-weights run under a
        # trained-net label (same fail-loudly rule as BENCH_DTYPE below)
        raise RuntimeError(f"unknown BENCH_NET {bench_net!r}")
    bench_dtype = os.environ.get("BENCH_DTYPE", "").lower()
    if bench_dtype in ("bf16", "bfloat16"):
        params = nnue.cast_params(params, jnp.bfloat16)
    elif bench_dtype == "int8":
        # retired after round 5 measured it at 37.2 knps vs 58-95 knps f32
        # (docs/profile-r5.md) — the engine gates the same path behind
        # FISHNET_TPU_EXPERIMENTAL_INT8 now; fail loudly rather than
        # record a number for a config production refuses to run
        raise RuntimeError("BENCH_DTYPE=int8 retired: measured slower than f32")
    elif bench_dtype not in ("", "f32", "float32"):
        # a typo'd dtype must not silently record an f32 run under the
        # wrong label — these artifacts are the round's perf record
        raise RuntimeError(f"unknown BENCH_DTYPE {bench_dtype!r}")
    max_ply = int(os.environ.get("BENCH_MAX_PLY", str(depth + 1)))
    # BENCH_HELPERS=K > 1: Lazy-SMP layout. The B fen-set lanes become the
    # PRIMARIES (rows [0, B)); K-1 replica blocks follow, so helper row
    # h*B + j re-searches primary j's root with perturbed move ordering
    # (ops/search.py order_jitter), sharing work only through the TT.
    # positions_done_per_s counts primaries only — helpers are the means,
    # not the deliverable — while nps keeps counting every lane (it is a
    # machine-throughput number).
    helpers = max(1, int(os.environ.get("BENCH_HELPERS", "1")))
    Bt = B * helpers
    order_jitter = None
    group = None
    required = None
    if helpers > 1:
        roots = jax.tree.map(
            lambda a: jnp.concatenate([a] * helpers, axis=0), roots)
        jit_arr = np.zeros(Bt, np.int32)
        grp_arr = np.arange(Bt, dtype=np.int32) % B
        for h in range(1, helpers):
            for j in range(B):
                jit_arr[h * B + j] = j * helpers + h  # nonzero ⇔ helper
        order_jitter = jnp.asarray(jit_arr)
        group = jnp.asarray(grp_arr)
        required = np.zeros(Bt, bool)
        required[:B] = True  # stop the moment every primary is DONE
    depth_arr = jnp.full((Bt,), depth, jnp.int32)
    budget_arr = jnp.full((Bt,), budget, jnp.int32)
    prefer_deep = helpers > 1
    tt_gen = 1 if helpers > 1 else 0

    # BENCH_MESH set → the refill A/B stage runs sharded over every local
    # device (shard-local refill, stacked boundary summaries); B must
    # divide over the devices. Only meaningful with BENCH_REFILL — the
    # lockstep single-batch stage below stays single-device
    mesh = None
    if os.environ.get("BENCH_MESH", "") not in ("", "0", "false", "no"):
        from fishnet_tpu.parallel.mesh import make_mesh

        mesh = make_mesh()
        if B % mesh.devices.size:
            raise RuntimeError(
                f"BENCH_MESH: width {B} must divide over "
                f"{mesh.devices.size} devices")
        _hb(t0, f"mesh: {mesh.devices.size} devices")

    # optional shared transposition table (BENCH_TT_LOG2=21 etc.); off by
    # default so the metric stays a raw search-throughput number. Mesh
    # stages take the per-device sharded table instead (each device
    # hashes into its private shard)
    tt = None
    tt_log2 = int(os.environ.get("BENCH_TT_LOG2", "0"))
    if tt_log2:
        if mesh is not None:
            from fishnet_tpu.parallel.mesh import make_sharded_table

            tt = make_sharded_table(mesh, tt_log2)
        else:
            from fishnet_tpu.ops import tt as tt_mod

            tt = tt_mod.make_table(tt_log2)

    # BENCH_REFILL set → the refill A/B stage instead of the lockstep
    # single-batch stage: same width, same workload (the FULL multipv
    # decomposition, more positions than lanes), measured chunk-serial
    # ("0") or streamed through the continuous-refill path ("1")
    refill_env = os.environ.get("BENCH_REFILL", "")
    if refill_env != "":
        _bench_refill(t0, params, B, depth, budget, variant, fen_set,
                      max_ply, tt, refill_env not in ("0", "false", "no"),
                      mode, platform, tt_log2, bench_dtype, mesh=mesh)
        return
    if mesh is not None:
        raise RuntimeError("BENCH_MESH requires BENCH_REFILL (the A/B "
                           "stage); the lockstep stage is single-device")
    _hb(t0, "inputs built")

    # compile each program explicitly so a compiler hang is distinguishable
    # from an execution hang in the heartbeat tail
    _hb(t0, "compile_start init_state")
    with trace.span("bench.compile", "bench", program="init_state"):
        state = S._init_state_jit(
            params, roots, depth_arr, budget_arr, max_ply, variant,
            order_jitter=order_jitter, group=group,
        )
        jax.block_until_ready(state.bt)
    _hb(t0, "compile_done init_state (and executed)")
    # short segments let the lane-narrowing path retire finished lanes
    # mid-batch (ops/search.py search_batch_resumable narrow=True) — with
    # one 20k-step segment a depth-3 batch finishes before the first
    # narrowing checkpoint and the finish-tail eats ~60% of wall clock
    seg = int(os.environ.get("BENCH_SEG", "1024"))
    _hb(t0, f"compile_start run_segment(seg={seg})")
    # the trailing args (deep_tt, prefer_deep, tt_gen) must mirror the
    # timed search_batch_resumable call exactly — tt_gen is a TRACED
    # operand, so even its weak-vs-strong int32 typing must match or
    # this precompile misses and a cold XLA compile lands in the timed
    # region
    with trace.span("bench.compile", "bench", program="run_segment",
                    seg=seg):
        lowered = S._run_segment_jit.lower(
            params, state, tt, seg, variant, False, prefer_deep,
            jnp.int32(tt_gen),
        )
        _hb(t0, "  lowered")
        compiled = lowered.compile()
    _hb(t0, "compile_done run_segment")
    # program cost accounting (obs/perf.py): the Compiled object is
    # already in hand, so the FLOPs/bytes/memory read is free — it
    # rides the RESULT row into the perf ledger and the
    # fishnet_program_* gauges
    program_cost = {}
    try:
        from fishnet_tpu.obs import perf as obs_perf
        from fishnet_tpu.utils import settings as _settings

        if _settings.get_bool("FISHNET_TPU_PERF_PROGRAMS"):
            program_cost = obs_perf.record_program_cost(
                "run_segment", compiled)
    except Exception as e:
        print(f"bench: program cost capture failed: {e}",
              file=sys.stderr, flush=True)
    # pre-compile every narrowed width down to the floor: the warmup and
    # timed runs can take DIFFERENT narrowing trajectories (a warm TT
    # changes when lanes finish), and a cold 10-40 s XLA compile landing
    # inside the timed region would corrupt the recorded nps. Narrowing
    # targets are powers of two >= 64 (ops/search.py), regardless of B.
    w = 64
    while w * 2 < Bt:
        w *= 2
    while w >= 64:
        sub = jax.tree.map(lambda a: a[:w], state)
        _hb(t0, f"compile_start run_segment(width={w})")
        with trace.span("bench.compile", "bench", program="run_segment",
                        width=w):
            S._run_segment_jit.lower(
                params, sub, tt, seg, variant, False, prefer_deep,
                jnp.int32(tt_gen),
            ).compile()
        w //= 2
    _hb(t0, "compile_done narrowed widths")

    helper_kw = dict(
        order_jitter=order_jitter, group=group, required=required,
        prefer_deep_store=prefer_deep, tt_gen=tt_gen,
    )
    _hb(t0, "exec_start warmup search")
    with trace.span("bench.warmup", "bench", B=Bt, depth=depth):
        out = S.search_batch_resumable(
            params, roots, depth_arr, budget_arr, max_ply=max_ply,
            segment_steps=seg, tt=tt, variant=variant, **helper_kw,
        )
        tt = out.pop("tt")
        jax.block_until_ready(out["nodes"])
    _hb(t0, f"exec_done warmup (steps={int(out['steps'])})")

    _hb(t0, "exec_start timed search")
    t1 = time.perf_counter()
    with trace.span("bench.search", "bench", B=Bt, depth=depth):
        out = S.search_batch_resumable(
            params, roots, depth_arr, budget_arr, max_ply=max_ply,
            segment_steps=seg, tt=tt, variant=variant, **helper_kw,
        )
        out.pop("tt")
        jax.block_until_ready(out["nodes"])
    dt = time.perf_counter() - t1
    total_nodes = int(np.asarray(out["nodes"]).sum())
    primary_nodes = int(np.asarray(out["nodes"])[:B].sum())
    _hb(t0, f"exec_done timed: {total_nodes:,} nodes in {dt:.2f}s")

    print(
        "RESULT "
        + json.dumps(
            {
                "nps": total_nodes / dt,
                "B": B,
                "depth": depth,
                "nodes": total_nodes,
                "dt": dt,
                "platform": platform,
                "variant": variant,
                "fen_set": fen_set,
                "row_mode": mode,
                "max_ply": max_ply,
                # primaries only: with helpers the first B rows are the
                # analysed positions; helper completions are not output
                "positions_done_per_s": round(
                    float(np.asarray(out["done"])[:B].sum()) / dt, 1
                ),
                "helpers": helpers,
                "primary_nodes": primary_nodes,
                "net": os.environ.get("BENCH_NET", "random"),
                "dtype": bench_dtype or "f32",
                "tt_log2": tt_log2,
                "program_cost": program_cost,
            }
        ),
        flush=True,
    )
    if rec is not None:
        path = rec.flight_dump(
            settings.get_str("FISHNET_TPU_TRACE_DIR"),
            f"bench-b{B}-d{depth}",
        )
        _hb(t0, f"trace dumped to {path}")


def run_stage(B: int, depth: int, budget: int, timeout: float,
              force_cpu: bool = False, select: bool = False,
              variant: str = "standard",
              fen_set: str = "standard",
              extra_env: dict | None = None) -> dict | None:
    """Parent: launch one stage subprocess; return its RESULT or None."""
    import tempfile

    t0 = time.monotonic()
    cmd = [sys.executable, os.path.abspath(__file__),
           "--stage", str(B), str(depth), str(budget), variant, fen_set]
    env = dict(os.environ)
    if force_cpu:
        env["BENCH_FORCE_CPU"] = "1"
    # "0" opts into the legacy scatter mode (select is the in-code
    # default since round 5 — see ops/search.py _SELECT_UPDATES)
    env["FISHNET_TPU_SELECT_UPDATES"] = "1" if select else "0"
    if extra_env:
        env.update({k: str(v) for k, v in extra_env.items()})
    # child stderr goes to a file, not a pipe: on timeout-kill a pipe's
    # contents are lost (TimeoutExpired.stderr is None on this platform),
    # and the heartbeat tail is most needed exactly then
    with tempfile.NamedTemporaryFile("w+", suffix=".bench-hb") as hb:
        try:
            r = subprocess.run(
                cmd, stdout=subprocess.PIPE, stderr=hb, text=True,
                timeout=timeout, env=env,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
        except subprocess.TimeoutExpired:
            hb.seek(0)
            tail = "".join(
                l for l in hb.read()[-4000:].splitlines(True)
                if "experimental" not in l
            )
            print(f"bench stage B={B} d={depth} "
                  f"mode={'select' if select else 'scatter'} TIMED OUT after "
                  f"{timeout:.0f}s; heartbeat tail:\n{tail}",
                  file=sys.stderr, flush=True)
            return None
        hb.seek(0)
        for line in hb.read().splitlines():
            if "experimental" not in line:
                print(line, file=sys.stderr, flush=True)
    if r.returncode != 0:
        print(f"bench stage B={B} d={depth} rc={r.returncode} "
              f"({time.monotonic() - t0:.0f}s)", file=sys.stderr, flush=True)
        return None
    for line in r.stdout.splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    print(f"bench stage B={B} d={depth}: no RESULT line", file=sys.stderr)
    return None


def run_serve_stage(timeout: float) -> dict | None:
    """Closed-loop latency row for the HTTP serving front-end
    (fishnet_tpu/serve/): boots `fishnet_tpu serve --backend python` as
    a subprocess, drives it with closed-loop client threads (each sends
    its next request the moment the previous one answers), and reports
    request latency p50/p99, the shed (429) rate, and positions/s. The
    python backend keeps the row measuring the serving layer itself —
    admission, HTTP framing, session fan-in — not device search speed;
    BENCH_SERVE_BACKEND overrides for an end-to-end device row."""
    import http.client
    import signal
    import threading

    backend = os.environ.get("BENCH_SERVE_BACKEND", "python")
    clients = int(os.environ.get("BENCH_SERVE_CLIENTS", "8"))
    per_client = int(os.environ.get("BENCH_SERVE_REQUESTS", "12"))
    start_fen = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"
    t0 = time.monotonic()

    proc = subprocess.Popen(
        [sys.executable, "-m", "fishnet_tpu", "serve",
         "--backend", backend, "--serve-port", "0", "--no-conf"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    try:
        host_port = None
        assert proc.stdout is not None
        while time.monotonic() - t0 < min(timeout, 120.0):
            line = proc.stdout.readline()
            if not line:
                break
            if "serve: listening on " in line:
                host_port = line.split("serve: listening on ", 1)[1].strip()
                break
        if host_port is None:
            print("bench serve_latency: server never came up",
                  file=sys.stderr, flush=True)
            return None
        host, _, port_s = host_port.rpartition(":")
        port = int(port_s)
        # drain the server's remaining stdout so it can't block on a
        # full pipe while we measure
        threading.Thread(
            target=lambda: proc.stdout.read(), daemon=True
        ).start()

        lock = threading.Lock()
        lat_ms: list = []
        shed = [0]
        failed = [0]
        positions = [0]

        def one_client(cid: int) -> None:
            conn = http.client.HTTPConnection(host, port, timeout=60.0)
            try:
                for i in range(per_client):
                    n_pos = 1 + (i % 2)
                    body = json.dumps({
                        "id": f"bench-{cid}-{i}",
                        "tenant": f"bench{cid % 2}",
                        # depth 1 keeps the python backend's share of
                        # the latency in the low ms, so p50/p99 track
                        # the serving layer rather than the fallback
                        # engine's search speed
                        "positions": [{"fen": start_fen, "moves": []}] * n_pos,
                        "depth": 1,
                        "timeout_ms": 30_000,
                    })
                    t1 = time.monotonic()
                    try:
                        conn.request("POST", "/analyse", body=body,
                                     headers={"Content-Type":
                                              "application/json"})
                        resp = conn.getresponse()
                        resp.read()
                    except (OSError, ValueError, http.client.HTTPException):
                        with lock:
                            failed[0] += 1
                        conn.close()
                        conn = http.client.HTTPConnection(
                            host, port, timeout=60.0)
                        continue
                    dt_ms = (time.monotonic() - t1) * 1000.0
                    with lock:
                        if resp.status == 200:
                            lat_ms.append(dt_ms)
                            positions[0] += n_pos
                        elif resp.status == 429:
                            shed[0] += 1
                        else:
                            failed[0] += 1
            finally:
                conn.close()

        t_load = time.monotonic()
        threads = [threading.Thread(target=one_client, args=(cid,))
                   for cid in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=timeout)
        wall_s = max(time.monotonic() - t_load, 1e-6)

        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=30.0)
        except subprocess.TimeoutExpired:
            print("bench serve_latency: server ignored SIGTERM",
                  file=sys.stderr, flush=True)
            return None
        if not lat_ms:
            print("bench serve_latency: no request completed",
                  file=sys.stderr, flush=True)
            return None
        lat_ms.sort()
        total = len(lat_ms) + shed[0] + failed[0]
        return {
            "backend": backend,
            "clients": clients,
            "requests_ok": len(lat_ms),
            "p50_ms": round(lat_ms[len(lat_ms) // 2], 2),
            "p99_ms": round(lat_ms[min(len(lat_ms) - 1,
                                       (len(lat_ms) * 99) // 100)], 2),
            "shed_rate": round(shed[0] / max(total, 1), 4),
            "failed": failed[0],
            "positions_per_s": round(positions[0] / wall_s, 1),
        }
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10.0)


def run_serve_slo_stage(timeout: float) -> dict | None:
    """SLO accounting row (round 14): closed-loop MIXED tenants against
    a live serve subprocess — an interactive tenant firing 1-position
    /bestmove requests under a tight deadline interleaved with a batch
    tenant firing 4-position /analyse requests under a loose one.
    Reports client-side p50/p99 per kind plus the server's own SLO
    accounting (obs/metrics.py SloRecorder) scraped from /metrics:
    deadline-miss rate and the queue-wait share of total latency —
    the two numbers the admission controller is supposed to keep low
    for interactive traffic even with batch load present."""
    import http.client
    import signal
    import socket
    import threading

    backend = os.environ.get("BENCH_SERVE_BACKEND", "python")
    clients = int(os.environ.get("BENCH_SLO_CLIENTS", "6"))
    per_client = int(os.environ.get("BENCH_SLO_REQUESTS", "10"))
    start_fen = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"
    t0 = time.monotonic()

    # reserve a loopback port for the metrics endpoint — the settings
    # switch only accepts a concrete positive port, so bind-and-release
    # an ephemeral one (the tiny reuse race is acceptable for a bench)
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    metrics_port = sock.getsockname()[1]
    sock.close()
    env = dict(os.environ, FISHNET_TPU_METRICS_PORT=str(metrics_port))

    proc = subprocess.Popen(
        [sys.executable, "-m", "fishnet_tpu", "serve",
         "--backend", backend, "--serve-port", "0", "--no-conf"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=os.path.dirname(os.path.abspath(__file__)), env=env,
    )
    try:
        host_port = None
        assert proc.stdout is not None
        while time.monotonic() - t0 < min(timeout, 120.0):
            line = proc.stdout.readline()
            if not line:
                break
            if "serve: listening on " in line:
                host_port = line.split("serve: listening on ", 1)[1].strip()
                break
        if host_port is None:
            print("bench serve_slo: server never came up",
                  file=sys.stderr, flush=True)
            return None
        host, _, port_s = host_port.rpartition(":")
        port = int(port_s)
        threading.Thread(
            target=lambda: proc.stdout.read(), daemon=True
        ).start()

        lock = threading.Lock()
        lat_ms: dict = {"analysis": [], "bestmove": []}
        shed = [0]
        failed = [0]

        def one_client(cid: int) -> None:
            interactive = cid % 2 == 0
            conn = http.client.HTTPConnection(host, port, timeout=60.0)
            try:
                for i in range(per_client):
                    if interactive:
                        kind, path = "bestmove", "/bestmove"
                        body = json.dumps({
                            "id": f"slo-i{cid}-{i}",
                            "tenant": "interactive",
                            "priority": "interactive",
                            "positions": [{"fen": start_fen, "moves": []}],
                            "level": 1,
                            # tight enough that queueing behind batch
                            # work shows up as deadline misses
                            "timeout_ms": 500,
                        })
                    else:
                        kind, path = "analysis", "/analyse"
                        body = json.dumps({
                            "id": f"slo-b{cid}-{i}",
                            "tenant": "batch",
                            "priority": "batch",
                            "positions": [
                                {"fen": start_fen, "moves": []}
                            ] * 4,
                            "depth": 1,
                            "timeout_ms": 30_000,
                        })
                    t1 = time.monotonic()
                    try:
                        conn.request("POST", path, body=body,
                                     headers={"Content-Type":
                                              "application/json"})
                        resp = conn.getresponse()
                        resp.read()
                    except (OSError, ValueError, http.client.HTTPException):
                        with lock:
                            failed[0] += 1
                        conn.close()
                        conn = http.client.HTTPConnection(
                            host, port, timeout=60.0)
                        continue
                    dt_ms = (time.monotonic() - t1) * 1000.0
                    with lock:
                        if resp.status == 200:
                            lat_ms[kind].append(dt_ms)
                        elif resp.status == 429:
                            shed[0] += 1
                        else:
                            failed[0] += 1
            finally:
                conn.close()

        threads = [threading.Thread(target=one_client, args=(cid,))
                   for cid in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=timeout)

        # scrape the server's SLO accounting BEFORE stopping it
        slo: dict = {}
        try:
            mconn = http.client.HTTPConnection(
                "127.0.0.1", metrics_port, timeout=10.0)
            mconn.request("GET", "/metrics")
            text = mconn.getresponse().read().decode("utf-8")
            mconn.close()
            for mline in text.splitlines():
                if mline.startswith("#") or "{" in mline:
                    continue  # skip comments and histogram buckets
                name, _, value = mline.partition(" ")
                if name.startswith("fishnet_slo_"):
                    slo[name] = float(value)
        except (OSError, ValueError, http.client.HTTPException) as e:
            print(f"bench serve_slo: metrics scrape failed: {e}",
                  file=sys.stderr, flush=True)

        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=30.0)
        except subprocess.TimeoutExpired:
            print("bench serve_slo: server ignored SIGTERM",
                  file=sys.stderr, flush=True)
            return None
        if not (lat_ms["analysis"] or lat_ms["bestmove"]):
            print("bench serve_slo: no request completed",
                  file=sys.stderr, flush=True)
            return None

        def pcts(vals: list) -> dict | None:
            if not vals:
                return None
            vals = sorted(vals)
            return {
                "requests_ok": len(vals),
                "p50_ms": round(vals[len(vals) // 2], 2),
                "p99_ms": round(vals[min(len(vals) - 1,
                                         (len(vals) * 99) // 100)], 2),
            }

        def slo_sum(what: str) -> float:
            return sum(v for k, v in slo.items()
                       if k.startswith(f"fishnet_slo_{what}_"))

        requests = slo_sum("requests_total")
        misses = slo_sum("deadline_miss_total")
        latency_sum = sum(v for k, v in slo.items()
                          if k.startswith("fishnet_slo_latency_ms_")
                          and k.endswith("_sum"))
        queue_sum = sum(v for k, v in slo.items()
                        if k.startswith("fishnet_slo_queue_ms_")
                        and k.endswith("_sum"))
        return {
            "backend": backend,
            "clients": clients,
            "interactive": pcts(lat_ms["bestmove"]),
            "batch": pcts(lat_ms["analysis"]),
            "shed": shed[0],
            "failed": failed[0],
            "deadline_miss_rate": (
                round(misses / requests, 4) if requests else None
            ),
            "queue_wait_share": (
                round(queue_sum / latency_sum, 4) if latency_sum else None
            ),
        }
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10.0)


def run_fleet_stage(timeout: float) -> dict | None:
    """Fleet scaling row (ISSUE 12): the same position workload pushed
    through the fleet coordinator (fishnet_tpu/fleet/) over 1/2/4
    fakehost-backed members with a fixed per-chunk service latency.
    Each member serializes its chunks (one in-flight dispatch, like the
    real supervised engine), so ideal scaling is linear in members;
    the row reports positions/s per member count, scaling efficiency
    vs the single-member run, and the redispatch count (0 — nothing
    dies here; the chaos gate owns the loss path). CPU-only, no JAX.

    Knobs: BENCH_FLEET=0 skips; BENCH_FLEET_MEMBERS="1,2,4" member
    counts; BENCH_FLEET_POSITIONS per-count workload (default 48);
    BENCH_FLEET_LATENCY_MS per-chunk member latency (default 30)."""
    import asyncio

    from fishnet_tpu.client.backoff import RandomizedBackoff
    from fishnet_tpu.client.ipc import Chunk, WorkPosition
    from fishnet_tpu.client.logger import Logger
    from fishnet_tpu.client.wire import AnalysisWork, EngineFlavor, NodeLimit
    from fishnet_tpu.fleet import FleetCoordinator
    from fishnet_tpu.fleet.member import make_local_member
    from fishnet_tpu.obs.metrics import MetricsRegistry

    counts = [int(c) for c in
              os.environ.get("BENCH_FLEET_MEMBERS", "1,2,4").split(",")]
    positions = int(os.environ.get("BENCH_FLEET_POSITIONS", "48"))
    latency_ms = float(os.environ.get("BENCH_FLEET_LATENCY_MS", "30"))
    start_fen = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"
    deadline_budget = min(timeout, 120.0)

    def one_chunk(i: int) -> Chunk:
        work = AnalysisWork(
            id=f"fleetbench{i:04d}",
            nodes=NodeLimit(sf16=4_000_000, classical=8_000_000),
            timeout_s=deadline_budget, depth=1, multipv=None,
        )
        return Chunk(
            work=work, deadline=time.monotonic() + deadline_budget,
            variant="standard", flavor=EngineFlavor.TPU,
            positions=[WorkPosition(
                work=work, position_index=0, url=None, skip=False,
                root_fen=start_fen, moves=[])],
        )

    async def measure(n_members: int) -> dict:
        members = [
            make_local_member(
                f"bench{i}",
                host_cmd=[
                    sys.executable, "-m", "fishnet_tpu.engine.fakehost",
                    "--script", '{"chunks": ["ok"]}',
                    "--hb-interval", "0.05",
                    "--latency-ms", str(latency_ms),
                ],
                logger=Logger(verbose=0),
                hb_interval=0.05, hb_timeout=2.0,
                backoff=RandomizedBackoff(max_s=0.1),
            )
            for i in range(n_members)
        ]
        coord = FleetCoordinator(
            members, logger=Logger(verbose=0),
            registry=MetricsRegistry(), loss_window=1.0,
        )
        try:
            await coord.start()  # spawn cost stays out of the window
            # one warm round so every member has served a chunk
            await asyncio.gather(
                *(coord.go_multiple(one_chunk(10_000 + i))
                  for i in range(n_members)))
            t0 = time.monotonic()
            await asyncio.gather(
                *(coord.go_multiple(one_chunk(i))
                  for i in range(positions)))
            wall_s = max(time.monotonic() - t0, 1e-6)
        finally:
            await coord.close()
        return {
            "positions_per_s": round(positions / wall_s, 1),
            "redispatches": coord.stats.redispatches,
            "losses": coord.stats.losses,
        }

    rows = {}
    base_pps = None
    for n in counts:
        try:
            row = asyncio.run(
                asyncio.wait_for(measure(n), timeout=deadline_budget))
        except (Exception, asyncio.TimeoutError) as e:
            print(f"bench fleet_scaling: {n}-member run failed: {e}",
                  file=sys.stderr, flush=True)
            return None
        if base_pps is None:
            base_pps = row["positions_per_s"]
        row["scaling_x"] = round(row["positions_per_s"] / base_pps, 2)
        row["efficiency"] = round(row["scaling_x"] / max(n / counts[0], 1),
                                  3)
        rows[str(n)] = row
    return {
        "latency_ms": latency_ms,
        "positions": positions,
        "members": rows,
    }


def run_fleet_tail_stage(timeout: float) -> dict | None:
    """Fleet tail-latency row (ISSUE 15): 3 fakehost members, one a
    deliberate straggler, the same chunk stream run with hedged
    dispatch off and on. Hedging duplicates the straggler's unfinished
    positions to a free member once deadline slack runs low
    (first-answer-wins through the exactly-once ledger), so the row
    reports per-chunk p50/p99 latency plus the loss and hedge counters
    for both modes — the p99 delta is the feature. CPU-only, no JAX.

    Knobs: BENCH_FLEET_TAIL=0 skips; BENCH_FLEET_TAIL_CHUNKS rounds
    (default 12); BENCH_FLEET_TAIL_LATENCY_MS straggler latency
    (default 200)."""
    import asyncio

    from fishnet_tpu.client.backoff import RandomizedBackoff
    from fishnet_tpu.client.ipc import Chunk, WorkPosition
    from fishnet_tpu.client.logger import Logger
    from fishnet_tpu.client.wire import AnalysisWork, EngineFlavor, NodeLimit
    from fishnet_tpu.fleet import FleetCoordinator
    from fishnet_tpu.fleet.member import make_local_member
    from fishnet_tpu.obs.metrics import MetricsRegistry

    rounds = int(os.environ.get("BENCH_FLEET_TAIL_CHUNKS", "12"))
    straggle_ms = float(os.environ.get("BENCH_FLEET_TAIL_LATENCY_MS", "200"))
    start_fen = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"
    ttl = 2.0

    def one_chunk(i: int, chunk_ttl: float) -> Chunk:
        work = AnalysisWork(
            id=f"fleettail{i:04d}",
            nodes=NodeLimit(sf16=4_000_000, classical=8_000_000),
            timeout_s=chunk_ttl, depth=1, multipv=None,
        )
        return Chunk(
            work=work, deadline=time.monotonic() + chunk_ttl,
            variant="standard", flavor=EngineFlavor.TPU,
            positions=[WorkPosition(
                work=work, position_index=p, url=None, skip=False,
                root_fen=start_fen, moves=[])
                for p in range(3)],
        )

    async def measure(hedge: bool) -> dict:
        members = [
            make_local_member(
                name,
                host_cmd=[
                    sys.executable, "-m", "fishnet_tpu.engine.fakehost",
                    "--script", '{"chunks": ["ok"]}',
                    "--hb-interval", "0.05",
                    "--latency-ms", str(ms),
                ],
                logger=Logger(verbose=0),
                hb_interval=0.05, hb_timeout=2.0,
                backoff=RandomizedBackoff(max_s=0.1),
            )
            for name, ms in (
                ("straggler", straggle_ms), ("fast0", 0), ("fast1", 0),
            )
        ]
        coord = FleetCoordinator(
            members, logger=Logger(verbose=0),
            registry=MetricsRegistry(), loss_window=5.0,
            # fire the hedge halfway into the straggler's service time,
            # well before the deadline — the hedge must be able to win
            hedge=hedge, hedge_slack_ms=int(ttl * 1000 - straggle_ms / 2),
        )
        lat = []
        try:
            await coord.start()
            # warm round outside the timing (ttl far past the trigger)
            await coord.go_multiple(one_chunk(9_000, 30.0))
            for i in range(rounds):
                t0 = time.monotonic()
                await coord.go_multiple(one_chunk(i, ttl))
                lat.append(time.monotonic() - t0)
        finally:
            await coord.close()
        lat.sort()
        return {
            "p50_ms": round(lat[len(lat) // 2] * 1000, 1),
            "p99_ms": round(lat[min(len(lat) - 1,
                                    int(len(lat) * 0.99))] * 1000, 1),
            "losses": coord.stats.losses,
            "hedges": coord.stats.hedges,
            "hedge_wins": coord.stats.hedge_wins,
        }

    rows = {}
    for mode, hedge in (("hedge_off", False), ("hedge_on", True)):
        try:
            rows[mode] = asyncio.run(
                asyncio.wait_for(measure(hedge),
                                 timeout=min(timeout, 120.0)))
        except (Exception, asyncio.TimeoutError) as e:
            print(f"bench fleet_tail: {mode} run failed: {e}",
                  file=sys.stderr, flush=True)
            return None
    return {
        "members": 3,
        "straggler_latency_ms": straggle_ms,
        "chunks": rounds,
        **rows,
    }


def run_autoscale_flash_stage(timeout: float) -> dict | None:
    """Elastic-capacity row (ISSUE 16): the identical open-loop flash
    crowd (tools/loadgen.py, 10x base rate, fixed seed) fired at a
    ServeApp fronting a one-member-floor fakehost fleet, autoscaler off
    vs on. The off run shows what a fixed floor does under a burst
    (queue growth, SLO deadline misses, sheds); the on run must show a
    strictly lower miss rate, the member count rising during the burst
    and returning to the floor afterwards, and at most one up/down
    reversal (the hysteresis asymmetry). Answers stay bit-identical —
    the autoscaler only changes membership, never dispatch planning
    (tests/test_autoscaler.py owns that assertion). CPU-only, no JAX.

    Knobs: BENCH_AUTOSCALE=0 skips; BENCH_AUTOSCALE_RPS base rate
    (default 2); BENCH_AUTOSCALE_LATENCY_MS member service latency
    (default 80)."""
    import asyncio

    from fishnet_tpu.client.backoff import RandomizedBackoff
    from fishnet_tpu.client.logger import Logger
    from fishnet_tpu.client.wire import EngineFlavor
    from fishnet_tpu.engine.session import EngineSession
    from fishnet_tpu.fleet import FleetCoordinator
    from fishnet_tpu.fleet.autoscaler import AutoscaleConfig, Autoscaler
    from fishnet_tpu.fleet.member import make_local_member
    from fishnet_tpu.obs.metrics import MetricsRegistry
    from fishnet_tpu.serve.server import ServeApp
    from tools.loadgen import LoadProfile, generate_schedule, run_load

    base_rps = float(os.environ.get("BENCH_AUTOSCALE_RPS", "2"))
    latency_ms = float(os.environ.get("BENCH_AUTOSCALE_LATENCY_MS", "80"))
    profile = LoadProfile(
        pattern="flash", duration_s=8.0, base_rps=base_rps,
        flash_factor=10.0, flash_start=0.125, flash_len=0.375,
        tenants=3, bestmove_ratio=0.0, positions=2, depth=1,
        timeout_ms=1500,
    )
    # one schedule, one seed: both modes replay the same arrivals
    schedule = generate_schedule(profile, seed=42)
    as_cfg = AutoscaleConfig(
        min_members=1, max_members=3, interval_s=0.15,
        up_queue=1, up_ticks=2, down_ticks=5,
        loss_cooldown_s=1.0, drain_timeout_s=20.0,
    )

    def member(name: str):
        return make_local_member(
            name,
            host_cmd=[
                sys.executable, "-m", "fishnet_tpu.engine.fakehost",
                "--script", '{"chunks": ["ok"]}',
                "--hb-interval", "0.05",
                "--latency-ms", str(latency_ms),
            ],
            logger=Logger(verbose=0),
            hb_interval=0.05, hb_timeout=2.0,
            backoff=RandomizedBackoff(max_s=0.1),
        )

    async def drive(autoscale_on: bool) -> dict:
        coord = FleetCoordinator(
            [member("as0")], logger=Logger(verbose=0),
            registry=MetricsRegistry(), loss_window=1.0,
            local_factory=member,
        )
        app = ServeApp(
            EngineSession(coord, flavor=EngineFlavor.TPU),
            # positions-denominated admission: 4 concurrent 2-position
            # requests; the member's serial chunk service is the real
            # bottleneck the autoscaler relieves
            max_inflight=8, max_queue=96,
            logger=Logger(verbose=0), registry=MetricsRegistry(),
        )
        autoscaler = (
            Autoscaler(coord, app.admission, config=as_cfg,
                       registry=app.registry, logger=Logger(verbose=0))
            if autoscale_on else None
        )
        members_trace = []

        def on_tick(t):
            n = len(coord.members)
            if not members_trace or members_trace[-1][1] != n:
                members_trace.append([round(t, 2), n])

        try:
            await coord.start()
            host, port = await app.start("127.0.0.1", 0)
            if autoscaler is not None:
                autoscaler.start()
            report = await run_load(
                host, port, schedule, logger=Logger(verbose=0),
                drain_timeout_s=60.0, on_tick=on_tick,
            )
            if autoscaler is not None:
                # post-burst: the loop must drain back to the floor
                floor_deadline = time.monotonic() + 25.0
                while time.monotonic() < floor_deadline:
                    snap = autoscaler.snapshot()
                    if (snap["members"] == as_cfg.min_members
                            and snap["draining"] is None):
                        break
                    await asyncio.sleep(0.1)
        finally:
            if autoscaler is not None:
                await autoscaler.stop()
            await app.drain_and_stop()
            await coord.close()

        snap = app.registry.snapshot()
        late = sum(v for k, v in snap.items()
                   if k.startswith("fishnet_slo_deadline_miss_total_"))
        d = report.as_dict()
        # deadline-miss rate over the whole schedule: answered-late
        # (SloRecorder deadline_miss), failed (the engine refuses to
        # search past an expired deadline — a 500 here IS a missed
        # deadline), and shed all violated the request's SLO
        violations = late + d["errors"] + d["shed"]
        row = {
            "ok": d["ok"],
            "shed": d["shed"],
            "errors": d["errors"],
            "answered_late": late,
            "p99_ms": d["per_kind"].get("analysis", {}).get("p99_ms", 0.0),
            "miss_rate": round(violations / max(len(schedule), 1), 4),
            "members_trace": members_trace,
            "members_final": len(coord.members),
        }
        if autoscaler is not None:
            seq = [dec.action for dec in autoscaler.decisions
                   if dec.action in ("up", "down")]
            row.update({
                "ups": autoscaler.stats.ups,
                "downs": autoscaler.stats.downs,
                # a second up-burst after a down is a flap: hysteresis
                # promises at most one reversal per burst
                "reversals": sum(
                    1 for a, b in zip(seq, seq[1:])
                    if a == "down" and b == "up"
                ),
                "member_seconds": round(autoscaler.stats.member_seconds, 1),
            })
        return row

    rows = {}
    for mode, flag in (("autoscale_off", False), ("autoscale_on", True)):
        try:
            rows[mode] = asyncio.run(
                asyncio.wait_for(drive(flag), timeout=min(timeout, 120.0)))
        except (Exception, asyncio.TimeoutError) as e:
            print(f"bench autoscale_flash: {mode} run failed: {e}",
                  file=sys.stderr, flush=True)
            return None
    return {
        "requests": len(schedule),
        "latency_ms": latency_ms,
        "floor": as_cfg.min_members,
        "ceiling": as_cfg.max_members,
        **rows,
    }


def run_cache_zipf_stage(timeout: float) -> dict | None:
    """Analysis-cache row (ISSUE 17): a Zipf-distributed position
    stream (tools/loadgen.py --fingerprint-dist zipf, s=1.1 — the
    opening-theory-dominated population the cache is built for)
    replayed closed-loop against an in-process ServeApp on the python
    backend, cache off vs on. Three legs over ONE schedule:

      cold  — cache off: every position is a real search (the
              pre-cache baseline);
      fill  — a fresh cache sees the same stream: the Zipf head starts
              repeating mid-run (`first_pass_hit_ratio` is the benefit
              a cache gets with NO warmup);
      warm  — the same stream again on the filled cache: the steady
              state of a long-running fleet.

    The acceptance bar is warm >= 5x cold on effective positions/s;
    the row also carries the hit ratio and resident bytes, and checks
    every warm answer bit-identical (scores/pvs/best_move/depth/nodes)
    to its cold twin. CPU-only, no JAX.

    Knobs: BENCH_CACHE=0 skips; BENCH_CACHE_REQUESTS (default 40);
    BENCH_CACHE_DEPTH (default 1 — keeps the python backend's search
    in the tens of ms, big enough to dwarf a ~1ms hit, small enough
    that the cold leg finishes in seconds)."""
    import asyncio

    from fishnet_tpu.cache.keys import engine_identity
    from fishnet_tpu.cache.store import AnalysisCache
    from fishnet_tpu.client.logger import Logger
    from fishnet_tpu.client.wire import EngineFlavor
    from fishnet_tpu.engine.pyengine import PyEngine
    from fishnet_tpu.engine.session import EngineSession
    from fishnet_tpu.obs.metrics import MetricsRegistry
    from fishnet_tpu.serve.server import ServeApp
    from tools.loadgen import LoadProfile, generate_schedule, request_body

    n_requests = int(os.environ.get("BENCH_CACHE_REQUESTS", "40"))
    depth = int(os.environ.get("BENCH_CACHE_DEPTH", "1"))
    profile = LoadProfile(
        pattern="steady", duration_s=60.0, base_rps=2.0,
        tenants=3, bestmove_ratio=0.0, positions=2, depth=depth,
        timeout_ms=30_000,
        fingerprint_dist="zipf", fingerprint_pool=24,
        fingerprint_zipf_s=1.1,
    )
    schedule = generate_schedule(profile, seed=42)[:n_requests]
    bodies = [request_body(req, i) for i, req in enumerate(schedule)]
    n_positions = sum(len(b["positions"]) for b in bodies)

    async def http_post(host, port, payload_obj):
        reader, writer = await asyncio.open_connection(host, port)
        try:
            payload = json.dumps(payload_obj).encode("utf-8")
            head = (
                f"POST /analyse HTTP/1.1\r\nHost: {host}\r\n"
                f"Content-Type: application/json\r\n"
                f"Content-Length: {len(payload)}\r\n"
                "Connection: close\r\n\r\n"
            )
            writer.write(head.encode("latin-1") + payload)
            await writer.drain()
            raw = await reader.read()
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass
        header, _, body_bytes = raw.partition(b"\r\n\r\n")
        status = int(header.decode("latin-1").split(None, 2)[1])
        return status, (json.loads(body_bytes) if body_bytes else {})

    def comparable(resp_body):
        # the search-determined payload; wall-clock fields (time_s,
        # nps, latency) legitimately differ between a cached answer
        # and a fresh search
        return [
            {k: r.get(k)
             for k in ("scores", "pvs", "best_move", "depth", "nodes")}
            for r in resp_body.get("results", [])
        ]

    async def replay(cache) -> dict:
        """One closed-loop pass over the schedule; returns wall time
        and the comparable answers keyed by request id."""
        app = ServeApp(
            EngineSession(PyEngine(max_depth=depth),
                          flavor=EngineFlavor.OFFICIAL),
            max_inflight=8, max_queue=16, default_timeout_ms=30_000,
            logger=Logger(verbose=0), registry=MetricsRegistry(),
            cache=cache,
        )
        answers = {}
        try:
            host, port = await app.start("127.0.0.1", 0)
            t0 = time.monotonic()
            for body in bodies:
                status, resp = await http_post(host, port, body)
                if status != 200:
                    raise RuntimeError(
                        f"request {body['id']} answered {status}")
                answers[body["id"]] = comparable(resp)
            wall_s = max(time.monotonic() - t0, 1e-6)
        finally:
            await app.drain_and_stop()
        return {"wall_s": wall_s, "answers": answers}

    async def drive() -> dict:
        cold = await replay(None)

        ident = engine_identity(PyEngine(max_depth=depth),
                                EngineFlavor.OFFICIAL)
        cache = AnalysisCache(ident)  # memory-only: the row measures
        fill = await replay(cache)    # the tier, not the sqlite sink
        c_fill = cache.counters()
        first_pass_ratio = c_fill["hit_ratio"]

        warm = await replay(cache)
        c_warm = cache.counters()
        warm_hits = c_warm["hits"] - c_fill["hits"]
        warm_total = warm_hits + (c_warm["misses"] - c_fill["misses"])

        identical = all(
            cold["answers"][rid] == warm["answers"][rid]
            for rid in cold["answers"]
        )
        cold_pps = n_positions / cold["wall_s"]
        warm_pps = n_positions / warm["wall_s"]
        return {
            "requests": len(bodies),
            "positions": n_positions,
            "depth": depth,
            "pool": profile.fingerprint_pool,
            "zipf_s": profile.fingerprint_zipf_s,
            "cold_pos_per_s": round(cold_pps, 1),
            "warm_pos_per_s": round(warm_pps, 1),
            "speedup": round(warm_pps / max(cold_pps, 1e-9), 1),
            "first_pass_hit_ratio": first_pass_ratio,
            "warm_hit_ratio": round(
                warm_hits / max(warm_total, 1), 4),
            "entries": c_warm["entries"],
            "bytes": c_warm["bytes"],
            "coalesced": c_warm["coalesced"],
            "bit_identical": identical,
        }

    try:
        return asyncio.run(
            asyncio.wait_for(drive(), timeout=min(timeout, 240.0)))
    except (Exception, asyncio.TimeoutError) as e:
        print(f"bench cache_zipf: run failed: {e}",
              file=sys.stderr, flush=True)
        return None


def mesh_scaling_child(ndev: int) -> None:
    """Child: the FULL multipv workload (229 root-move boards of the
    standard 8-FEN set) streamed through one registry-driven engine on
    an `ndev`-device mesh at width 8*ndev — the pod-slice shape where
    one logical engine's lane count grows with its device count.

    Prints one RESULT line. positions_per_kstep (positions retired per
    1000 per-shard device steps) is the hardware-independent scaling
    metric: on a real pod each shard is a chip and wall-clock tracks
    per-shard steps, while on a forced-device CPU host all shards
    time-share one core, so wall positions/s (also reported) cannot show
    device parallelism. Mean live occupancy per shard comes straight
    from the stream's boundary summaries."""
    # must land before the first jax import in this process
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    t0 = time.monotonic()
    import numpy as np

    import jax  # noqa: F401  (device init under the forced flag)
    from fishnet_tpu.models import nnue
    from fishnet_tpu.ops import search as S
    from fishnet_tpu.parallel.mesh import make_mesh, make_sharded_table

    _hb(t0, f"mesh_scaling ndev={ndev}: building workload")
    width = 8 * ndev
    roots, n_all = _all_boards_for(width, "standard", "multipv")
    # first 96 root-move boards: > width at every ndev (so refill fires
    # everywhere), small enough that the width-8 run — ~12 serial fill
    # generations on one core — fits the stage budget. The CI perf gate
    # (BENCH_GATE) trims further: the scaling story is unchanged and the
    # deterministic counters stay deterministic at any fixed count.
    n_pos = min(int(os.environ.get("BENCH_MESH_SCALING_POS", "96")), n_all)
    roots = jax.tree_util.tree_map(lambda a: a[:n_pos], roots)
    # depth 1, staggered node budgets: 96 distinct root-move boards
    # park at different boundaries on different shards (different move
    # counts, different budgets), so refill and the finished-lane
    # gathers interleave — deeper lanes would push the width-8 run to
    # many minutes on a 1-core host without changing the scaling story
    depths = np.ones(n_pos, np.int32)
    budget = np.asarray(
        [1_500 + 250 * (i % 7) for i in range(n_pos)], np.int32)
    params = nnue.init_params(
        jax.random.PRNGKey(3), l1=32, feature_set="board768")
    mesh = make_mesh(ndev)
    kw = dict(max_ply=6, width=width, segment_steps=30, mesh=mesh,
              pipeline=True)

    # warmup: the SAME shapes (compilation is shape-keyed) at a budget
    # low enough to drain in seconds — still deep enough to fire refill
    # and the finished-lane gathers, so every program is warm before
    # the timed pass
    _hb(t0, f"exec_start warmup stream (width={width}, N={n_pos})")
    S.search_stream(params, roots, depths,
                    np.full(n_pos, 200, np.int32),
                    tt=make_sharded_table(mesh, 10), **kw)
    _hb(t0, "exec_start timed stream")
    t1 = time.perf_counter()
    out = S.search_stream(params, roots, depths, budget,
                          tt=make_sharded_table(mesh, 10), **kw)
    dt = time.perf_counter() - t1
    _hb(t0, f"exec_done timed: {dt:.2f}s")

    done = int(np.asarray(out["done"]).sum())
    steps = int(np.asarray(out["steps"]))  # per-shard device steps
    occ = out["occupancy"]
    lane_steps = sum(r["live"] * r["steps"] for r in occ)
    denom = max(sum(width * r["steps"] for r in occ), 1)
    local = width // ndev
    shard_occ = [
        round(sum(r["shard_live"][s] * r["steps"] for r in occ)
              / max(sum(local * r["steps"] for r in occ), 1), 3)
        for s in range(ndev)
    ]
    print(
        "RESULT "
        + json.dumps({
            "ndev": ndev,
            "width": width,
            "positions": n_pos,
            "done": done,
            "dt": round(dt, 2),
            "positions_per_s": round(n_pos / dt, 2),
            "steps_per_shard": steps,
            "positions_per_kstep": round(n_pos / max(steps, 1) * 1000, 2),
            "mean_live_occupancy": round(lane_steps / denom, 3),
            "shard_live_occupancy": shard_occ,
            "refills": int(out["refills"]),
            "boundaries": len(occ),
        }),
        flush=True,
    )


def run_mesh_scaling_stage(timeout: float) -> dict | None:
    """Mesh scaling row (partition-rule registry): the SAME multipv
    workload through one registry-derived sharded engine at ndev =
    1/2/4/8 virtual devices, width 8*ndev. scaling_x is the
    positions-per-shard-step ratio vs ndev=1 — the wall-clock scaling a
    real pod slice sees, measured on CPU where the shards time-share
    one core (wall positions/s rides along per row for reference).

    Knobs: BENCH_MESH_SCALING=0 skips; BENCH_MESH_SCALING_NDEV
    (default "1,2,4,8")."""
    import tempfile

    counts = [int(c) for c in os.environ.get(
        "BENCH_MESH_SCALING_NDEV", "1,2,4,8").split(",")]
    here = os.path.dirname(os.path.abspath(__file__))
    t0 = time.monotonic()
    rows: dict = {}
    base_ppk = None
    for ndev in counts:
        remaining = timeout - (time.monotonic() - t0)
        if remaining < 60.0:
            print(f"bench mesh_scaling: skipping ndev={ndev} "
                  "(stage budget spent)", file=sys.stderr, flush=True)
            break
        env = dict(os.environ)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        with tempfile.NamedTemporaryFile("w+", suffix=".bench-hb") as hb:
            try:
                r = subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--mesh-scaling-stage", str(ndev)],
                    stdout=subprocess.PIPE, stderr=hb, text=True,
                    timeout=remaining, env=env, cwd=here,
                )
            except subprocess.TimeoutExpired:
                hb.seek(0)
                tail = hb.read()[-2000:]
                print(f"bench mesh_scaling: ndev={ndev} TIMED OUT; "
                      f"heartbeat tail:\n{tail}",
                      file=sys.stderr, flush=True)
                break  # keep the rows already measured
        if r.returncode != 0:
            print(f"bench mesh_scaling: ndev={ndev} rc={r.returncode}",
                  file=sys.stderr, flush=True)
            break
        row = None
        for line in r.stdout.splitlines():
            if line.startswith("RESULT "):
                row = json.loads(line[len("RESULT "):])
        if row is None:
            print(f"bench mesh_scaling: ndev={ndev}: no RESULT line",
                  file=sys.stderr, flush=True)
            break
        if row["done"] != row["positions"]:
            print(f"bench mesh_scaling: ndev={ndev} left "
                  f"{row['positions'] - row['done']} unfinished",
                  file=sys.stderr, flush=True)
            break
        if base_ppk is None:
            base_ppk = row["positions_per_kstep"]
        row["scaling_x"] = round(
            row["positions_per_kstep"] / max(base_ppk, 1e-9), 2)
        rows[str(ndev)] = row
    if not rows:
        return None
    return {"ndev": rows}


def run_coldstart_stage(timeout: float) -> dict | None:
    """Cold-start A/B row (AOT program assets, fishnet_tpu/aot/):
    time-to-first-result of a FRESH engine process, plain JIT vs booted
    against a pre-packed bundle. Three subprocesses: `fishnet_tpu pack`
    builds the bundle, then two tools/aot_smoke.py --child runs (one
    with FISHNET_TPU_AOT=0, one against the bundle) each boot, warm up,
    and search 16 lanes to the first result. Both children disable the
    persistent XLA cache so the A/B isolates the bundle itself — with
    the disk cache on, the JIT side is half-warm too and the row
    under-reports what a fresh autoscaled replica actually saves.
    BENCH_COLDSTART_PLY sets the stack height (default 8, toy; 32 for
    the production shape — pack time grows with it)."""
    import shutil
    import tempfile

    ply = os.environ.get("BENCH_COLDSTART_PLY", "8")
    here = os.path.dirname(os.path.abspath(__file__))
    child = os.path.join(here, "tools", "aot_smoke.py")
    tmp = tempfile.mkdtemp(prefix="bench-coldstart-")
    store = os.path.join(tmp, "store")
    env = {
        **os.environ,
        "FISHNET_TPU_MAX_PLY": ply,
        "FISHNET_TPU_WARMUP_BUCKETS": "16",
        "FISHNET_TPU_HELPERS": "1",
        "FISHNET_TPU_NO_COMPILE_CACHE": "1",
    }
    env.pop("FISHNET_TPU_TRACE_DIR", None)

    def run_one(tag: str, argv: list, extra: dict,
                budget: float) -> tuple[float, int] | None:
        t1 = time.monotonic()
        try:
            r = subprocess.run(
                argv, cwd=here, env={**env, **extra},
                capture_output=True, text=True, timeout=budget,
            )
        except subprocess.TimeoutExpired:
            print(f"bench cold_start: {tag} timed out",
                  file=sys.stderr, flush=True)
            return None
        if r.returncode != 0:
            tail = (r.stdout or "").splitlines()[-3:]
            print(f"bench cold_start: {tag} exited {r.returncode}: {tail}",
                  file=sys.stderr, flush=True)
            return None
        return time.monotonic() - t1, r.returncode

    try:
        t0 = time.monotonic()
        packed = run_one(
            "pack",
            [sys.executable, "-m", "fishnet_tpu", "pack",
             "--aot-bundle", store, "--no-conf"],
            {"FISHNET_TPU_AOT": "0"}, timeout,
        )
        if packed is None:
            return None
        pack_s = packed[0]
        budget = max(60.0, timeout - (time.monotonic() - t0))
        cold = run_one(
            "jit-cold",
            [sys.executable, child, "--child",
             os.path.join(tmp, "cold.json")],
            {"FISHNET_TPU_AOT": "0"}, budget,
        )
        budget = max(60.0, timeout - (time.monotonic() - t0))
        warm = run_one(
            "aot-warm",
            [sys.executable, child, "--child",
             os.path.join(tmp, "warm.json")],
            {"FISHNET_TPU_AOT": "1", "FISHNET_TPU_AOT_DIR": store},
            budget,
        )
        if cold is None or warm is None:
            return None
        with open(os.path.join(tmp, "warm.json")) as f:
            warm_rep = json.load(f)
        if warm_rep.get("stats", {}).get("misses", 0):
            # a missing program means the row is measuring a partial
            # bundle, not warmup-free boot — report it as a failure
            print(f"bench cold_start: warm boot missed: "
                  f"{warm_rep['stats']}", file=sys.stderr, flush=True)
            return None
        return {
            "pack_s": round(pack_s, 2),
            "cold_first_result_s": round(cold[0], 2),
            "warm_first_result_s": round(warm[0], 2),
            "speedup": round(cold[0] / max(warm[0], 1e-9), 2),
            "programs": warm_rep.get("aot", {}).get("programs", 0),
            "loads": warm_rep.get("stats", {}).get("loads", 0),
            "max_ply": int(ply),
            "lanes": 16,
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def device_preflight(timeout: float = 120.0) -> bool:
    """Can a fresh process see the TPU at all? A wedged/down tunnel makes
    jax init hang, which would otherwise burn one full stage timeout per
    ramp stage before the CPU fallback ever runs."""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(len(jax.devices()))"],
            capture_output=True, text=True, timeout=timeout,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _ledger_record(results: dict, source: str = "bench",
                   emit: bool = False) -> None:
    """Append one run's RESULT rows to the perf ledger (obs/perf.py)
    and, when emit is set, write the next BENCH_rNN.json artifact from
    it. Backfills the checked-in BENCH/MULTICHIP history first
    (idempotent) so the trend series is populated even on a fresh
    checkout. Never raises: a broken ledger must not cost the bench
    run its stdout contract."""
    try:
        from fishnet_tpu.obs import perf as obs_perf
    except Exception as e:
        print(f"bench: perf ledger unavailable: {e}",
              file=sys.stderr, flush=True)
        return
    try:
        ledger = obs_perf.PerfLedger.open()
        try:
            ledger.backfill()
            run_id = f"{source}-{int(time.time())}"
            n = ledger.ingest_results(
                run_id, results, source=source,
                info=obs_perf.build_info(),
            )
            print(f"bench: perf ledger {ledger.path}: recorded {n} "
                  f"metrics as {run_id}", file=sys.stderr, flush=True)
            if emit and n:
                path = ledger.emit_bench_round(run_id)
                if path:
                    print(f"bench: emitted {path} from the ledger",
                          file=sys.stderr, flush=True)
        finally:
            ledger.close()
    except Exception as e:
        print(f"bench: perf ledger write failed: {e}",
              file=sys.stderr, flush=True)


def gate_main() -> None:
    """CI perf-gate rows (BENCH_GATE=1): only the quick deterministic
    counters — a toy search stage (total nodes, positions done) and a
    1/2-device mesh-scaling pair (positions_per_kstep, steps, refills,
    occupancy) — appended to the perf ledger under gate_* row names so
    they build their own baseline series, never mixed with full bench
    rows. tools/perf_report.py --check gates the counter tier against
    the rolling baseline; wall-clock values ride along report-only
    (docs/perf.md)."""
    t_start = time.monotonic()
    timeout = float(os.environ.get("BENCH_GATE_TIMEOUT", "900"))
    results: dict = {}

    res = run_stage(8, 2, 3000, timeout * 0.5, select=SELECT_FIRST,
                    extra_env={"BENCH_SEG": "64"})
    if res is not None:
        results["gate_search"] = res
    print("bench config gate_search: "
          + (json.dumps(res) if res else "FAILED"),
          file=sys.stderr, flush=True)

    os.environ.setdefault("BENCH_MESH_SCALING_NDEV", "1,2")
    os.environ.setdefault("BENCH_MESH_SCALING_POS", "32")
    remaining = timeout - (time.monotonic() - t_start)
    mesh = None
    if remaining > 60.0:
        mesh = run_mesh_scaling_stage(remaining)
    if mesh is not None:
        results["gate_mesh"] = mesh
    print("bench config gate_mesh: "
          + (json.dumps(mesh) if mesh else "FAILED"),
          file=sys.stderr, flush=True)

    _ledger_record(results, source="gate")
    print(json.dumps({
        "metric": "perf-gate deterministic rows",
        "value": len(results),
        "unit": "rows",
        "vs_baseline": 1.0 if results else 0.0,
    }))


def main() -> None:
    # 1024 lanes = the measured v5e throughput sweet spot
    # (docs/profile-r5.md; 2048 falls off a VMEM cliff)
    B = int(os.environ.get("BENCH_LANES", "1024"))
    DEPTH = int(os.environ.get("BENCH_DEPTH", "4"))
    BUDGET = int(os.environ.get("BENCH_BUDGET", "200000"))
    stage_timeout = float(os.environ.get("BENCH_STAGE_TIMEOUT", "420"))
    total_budget = float(os.environ.get("BENCH_TOTAL_BUDGET", "1800"))
    t_start = time.monotonic()

    stages = [s for s in STAGES if s[0] <= B]
    if (B, DEPTH) not in stages:
        stages.append((B, DEPTH))

    if not device_preflight():
        print("bench: device preflight failed (tunnel down/wedged); "
              "skipping device stages", file=sys.stderr, flush=True)
        stages = []

    best = None  # result dict with max nps
    fails = 0
    # the row-write mode that last worked on this device; start from the
    # candidate-fix mode (SELECT_FIRST) and fall back per shape
    good_mode: bool | None = None
    for b, d in stages:
        if time.monotonic() - t_start > total_budget - stage_timeout:
            print("bench: total budget nearly spent; stopping ramp",
                  file=sys.stderr, flush=True)
            break
        preferred = SELECT_FIRST if good_mode is None else good_mode
        modes = [preferred, not preferred]  # retry a dead shape in the other mode
        res = None
        for m in modes:
            res = run_stage(b, d, BUDGET, stage_timeout, select=m)
            if res is not None:
                good_mode = m
                break
            if time.monotonic() - t_start > total_budget - stage_timeout:
                break
        if res is None:
            fails += 1
            if fails >= 2:
                # two consecutive dead shapes (both modes): the device (or
                # tunnel) is gone; don't burn the rest of the budget on it
                print("bench: two consecutive stage failures; stopping ramp",
                      file=sys.stderr, flush=True)
                break
            continue
        fails = 0
        if best is None or res["nps"] > best["nps"]:
            best = res

    # BASELINE.md config matrix (configs 3-5): multipv-5 decomposition,
    # chess960, crazyhouse + threeCheck — each its own subprocess in the
    # mode that worked for the headline ramp. Results go to
    # bench_matrix.json (the driver consumes only the single stdout line).
    matrix = {}
    if best is not None and os.environ.get("BENCH_MATRIX", "1") != "0":
        # (name, B, depth, variant, fen_set, extra_env):
        # cfg3-5 = BASELINE.md's config matrix; dtype stages answer
        # VERDICT r4 #4 (int8/bf16 never perf-measured); production =
        # VERDICT r4 #5 (MAX_PLY=32 stack, shipped net, shared TT — the
        # configuration chunk-serving actually runs, vs the toy shapes)
        cfg_stages = [
            ("cfg3_multipv5", 128, 3, "standard", "multipv", None),
            ("cfg4_chess960", 64, 3, "standard", "960", None),
            ("cfg5_crazyhouse", 64, 3, "crazyhouse", "variant", None),
            ("cfg5_threecheck", 64, 3, "threeCheck", "variant", None),
            ("dtype_bf16", 64, 3, "standard", "standard",
             {"BENCH_DTYPE": "bf16"}),
            # dtype_int8 row retired: round 5 measured 37.2 knps vs
            # 58-95 knps f32, and the engine now gates the int8 path
            # behind FISHNET_TPU_EXPERIMENTAL_INT8 (it is a net loss)
            # multipv fen_set: DISTINCT positions per lane — repeating the
            # 8 standard FENs across lanes lets the shared TT dedup whole
            # subtrees, which deflates the nodes/sec metric while doing
            # the same per-position work (round-5 measurement note).
            # B=192: the 8 FENs decompose into 229 root-move boards, so
            # 192 is the largest stage width with no duplicate padding
            ("production_d6_mp32", 192, 6, "standard", "multipv",
             {"BENCH_MAX_PLY": "32", "BENCH_NET": "default",
              "BENCH_TT_LOG2": "21"}),
            # continuous lane refill A/B (round 7): the SAME production
            # workload — all 229 root-move boards, MORE positions than
            # the 192 lanes — drained chunk-serially in width-192 batches
            # (the last batch runs 80% padding) vs streamed through one
            # full-width program with DONE lanes respliced at segment
            # boundaries (ops/search.py search_stream). Acceptance:
            # refill-on positions_done_per_s >= 1.3x refill-off at the
            # same width, with occupancy counters in the refill row.
            # Ahead of helper_lanes_k4 (recorded in round 6) so a tight
            # BENCH_TOTAL_BUDGET skips the rerun, not this round's A/B
            ("production_d6_mp32_serial", 192, 6, "standard", "multipv",
             {"BENCH_MAX_PLY": "32", "BENCH_NET": "default",
              "BENCH_TT_LOG2": "21", "BENCH_REFILL": "0"}),
            # FISHNET_TPU_PIPELINE pinned OFF: this row stays the
            # round-7 synchronous-boundary baseline for the pipelined
            # row below (same workload, same width, same refill path)
            ("production_d6_mp32_refill", 192, 6, "standard", "multipv",
             {"BENCH_MAX_PLY": "32", "BENCH_NET": "default",
              "BENCH_TT_LOG2": "21", "BENCH_REFILL": "1",
              "FISHNET_TPU_PIPELINE": "0"}),
            # asynchronous segment pipeline A/B (round 8): identical
            # stream workload with double-buffered dispatch — packed
            # boundary summaries, donated segment buffers and
            # speculative next-segment dispatch (ops/search.py
            # search_stream pipeline=True). Compare host_ms /
            # device_ms / transfers in the occupancy summary against
            # the _refill row; acceptance is >=1.2x positions_done_per_s
            # at the identical node total on the toy CPU shape
            ("production_d6_mp32_pipelined", 192, 6, "standard",
             "multipv",
             {"BENCH_MAX_PLY": "32", "BENCH_NET": "default",
              "BENCH_TT_LOG2": "21", "BENCH_REFILL": "1",
              "FISHNET_TPU_PIPELINE": "1"}),
            # mesh parity A/B (round 10): the production refill workload
            # sharded over 8 devices (XLA_FLAGS forces 8 virtual CPU
            # devices when no real mesh is present; on a TPU pod slice
            # the flag is inert and the real chips shard). _mesh_serial
            # drains chunk-serial width-192 sharded batches; _mesh_refill
            # streams with shard-local refill (parallel/mesh.py). The
            # refill row's occupancy summary carries per-shard mean live
            # fractions and the boundary transfer count — acceptance is
            # refill mean_live_frac strictly above serial at the same
            # width, with transfers = 1 on no-finish boundaries
            ("production_d6_mp32_mesh_serial", 192, 6, "standard",
             "multipv",
             {"BENCH_MAX_PLY": "32", "BENCH_NET": "default",
              "BENCH_TT_LOG2": "21", "BENCH_REFILL": "0",
              "BENCH_MESH": "1",
              "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}),
            ("production_d6_mp32_mesh_refill", 192, 6, "standard",
             "multipv",
             {"BENCH_MAX_PLY": "32", "BENCH_NET": "default",
              "BENCH_TT_LOG2": "21", "BENCH_REFILL": "1",
              "BENCH_MESH": "1",
              "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}),
            # same production shape with 3 Lazy-SMP helper lanes riding
            # each of the 192 primaries (768 lanes total, shared 2M-slot
            # TT): the round-6 acceptance comparison is this row's
            # positions_done_per_s and completed depth vs
            # production_d6_mp32 at the same deadline
            ("helper_lanes_k4", 192, 6, "standard", "multipv",
             {"BENCH_MAX_PLY": "32", "BENCH_NET": "default",
              "BENCH_TT_LOG2": "21", "BENCH_HELPERS": "4"}),
        ]
        for name, b, d, var, fset, xenv in cfg_stages:
            remaining = total_budget - (time.monotonic() - t_start)
            if remaining < 120.0:
                print(f"bench: skipping {name} (budget spent)",
                      file=sys.stderr, flush=True)
                matrix[name] = None
                continue
            res = run_stage(
                b, d, BUDGET, min(stage_timeout, remaining),
                select=(good_mode if good_mode is not None else SELECT_FIRST),
                variant=var, fen_set=fset, extra_env=xenv,
            )
            matrix[name] = res
            print(f"bench config {name}: "
                  + (json.dumps(res) if res else "FAILED"),
                  file=sys.stderr, flush=True)

    # serving-layer latency row (round 11): host-side closed loop over
    # the HTTP front-end; runs on the python backend so it measures
    # admission + framing + session fan-in, independent of the device
    if os.environ.get("BENCH_SERVE", "1") != "0":
        remaining = total_budget - (time.monotonic() - t_start)
        if remaining < 120.0:
            print("bench: skipping serve_latency (budget spent)",
                  file=sys.stderr, flush=True)
            matrix["serve_latency"] = None
        else:
            res = run_serve_stage(min(stage_timeout, remaining))
            matrix["serve_latency"] = res
            print("bench config serve_latency: "
                  + (json.dumps(res) if res else "FAILED"),
                  file=sys.stderr, flush=True)

    # SLO accounting row (round 14): mixed interactive/batch tenants in
    # one closed loop; deadline-miss rate and queue-wait share come from
    # the server's own SloRecorder via /metrics, p50/p99 per kind from
    # the client side
    if os.environ.get("BENCH_SERVE_SLO", "1") != "0":
        remaining = total_budget - (time.monotonic() - t_start)
        if remaining < 120.0:
            print("bench: skipping serve_slo (budget spent)",
                  file=sys.stderr, flush=True)
            matrix["serve_slo"] = None
        else:
            res = run_serve_slo_stage(min(stage_timeout, remaining))
            matrix["serve_slo"] = res
            print("bench config serve_slo: "
                  + (json.dumps(res) if res else "FAILED"),
                  file=sys.stderr, flush=True)

    # fleet scaling row (round 12): 1/2/4 fakehost members behind the
    # coordinator; ideal scaling is linear (each member serializes its
    # chunks at a fixed service latency), so positions/s and efficiency
    # here measure the coordinator's admission + ledger overhead
    if os.environ.get("BENCH_FLEET", "1") != "0":
        remaining = total_budget - (time.monotonic() - t_start)
        if remaining < 120.0:
            print("bench: skipping fleet_scaling (budget spent)",
                  file=sys.stderr, flush=True)
            matrix["fleet_scaling"] = None
        else:
            res = run_fleet_stage(min(stage_timeout, remaining))
            matrix["fleet_scaling"] = res
            print("bench config fleet_scaling: "
                  + (json.dumps(res) if res else "FAILED"),
                  file=sys.stderr, flush=True)

    # fleet tail row (ISSUE 15): the same 3-member fleet with one
    # straggler, hedge off vs on — the p99 delta is the hedged-dispatch
    # feature, next to fleet_scaling's throughput story
    if os.environ.get("BENCH_FLEET_TAIL",
                      os.environ.get("BENCH_FLEET", "1")) != "0":
        remaining = total_budget - (time.monotonic() - t_start)
        if remaining < 60.0:
            print("bench: skipping fleet_tail (budget spent)",
                  file=sys.stderr, flush=True)
            matrix["fleet_tail"] = None
        else:
            res = run_fleet_tail_stage(min(stage_timeout, remaining))
            matrix["fleet_tail"] = res
            print("bench config fleet_tail: "
                  + (json.dumps(res) if res else "FAILED"),
                  file=sys.stderr, flush=True)

    # autoscale flash row (ISSUE 16): the same open-loop flash crowd,
    # autoscaler off vs on — the miss-rate delta and the member-count
    # trace are the elastic-capacity feature next to fleet_scaling's
    # static-membership story
    if os.environ.get("BENCH_AUTOSCALE",
                      os.environ.get("BENCH_FLEET", "1")) != "0":
        remaining = total_budget - (time.monotonic() - t_start)
        if remaining < 60.0:
            print("bench: skipping autoscale_flash (budget spent)",
                  file=sys.stderr, flush=True)
            matrix["autoscale_flash"] = None
        else:
            res = run_autoscale_flash_stage(min(stage_timeout, remaining))
            matrix["autoscale_flash"] = res
            print("bench config autoscale_flash: "
                  + (json.dumps(res) if res else "FAILED"),
                  file=sys.stderr, flush=True)

    # analysis-cache row (ISSUE 17): a Zipf position stream replayed
    # cache-off vs cache-on — the warm-vs-cold positions/s ratio is
    # the memoization feature next to serve_latency's cold-path story
    if os.environ.get("BENCH_CACHE", "1") != "0":
        remaining = total_budget - (time.monotonic() - t_start)
        if remaining < 60.0:
            print("bench: skipping cache_zipf (budget spent)",
                  file=sys.stderr, flush=True)
            matrix["cache_zipf"] = None
        else:
            res = run_cache_zipf_stage(min(stage_timeout, remaining))
            matrix["cache_zipf"] = res
            print("bench config cache_zipf: "
                  + (json.dumps(res) if res else "FAILED"),
                  file=sys.stderr, flush=True)

    # mesh scaling row (partition-rule registry): one registry-driven
    # engine over 1/2/4/8 virtual devices at width 8*ndev, same multipv
    # workload — positions-per-shard-step scaling is the pod-slice
    # story next to fleet_scaling's many-engines story
    if os.environ.get("BENCH_MESH_SCALING", "1") != "0":
        remaining = total_budget - (time.monotonic() - t_start)
        if remaining < 120.0:
            print("bench: skipping mesh_scaling (budget spent)",
                  file=sys.stderr, flush=True)
            matrix["mesh_scaling"] = None
        else:
            res = run_mesh_scaling_stage(min(stage_timeout * 2, remaining))
            matrix["mesh_scaling"] = res
            print("bench config mesh_scaling: "
                  + (json.dumps(res) if res else "FAILED"),
                  file=sys.stderr, flush=True)

    # cold-start A/B row (AOT program assets, round 13): time-to-first-
    # result of a fresh engine subprocess, plain JIT vs a pre-packed
    # bundle. Opt-in (BENCH_COLDSTART=1) — the pack leg recompiles the
    # full program set once more, which a tight-budget ramp shouldn't pay
    if os.environ.get("BENCH_COLDSTART", "0") not in ("", "0", "false",
                                                      "no"):
        remaining = total_budget - (time.monotonic() - t_start)
        if remaining < 120.0:
            print("bench: skipping cold_start (budget spent)",
                  file=sys.stderr, flush=True)
            matrix["cold_start"] = None
        else:
            res = run_coldstart_stage(min(stage_timeout * 2, remaining))
            matrix["cold_start"] = res
            print("bench config cold_start: "
                  + (json.dumps(res) if res else "FAILED"),
                  file=sys.stderr, flush=True)
    if matrix:
        try:
            with open(os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "bench_matrix.json"), "w") as f:
                json.dump({"headline": best, "configs": matrix}, f, indent=1)
        except OSError as e:
            print(f"bench: could not write bench_matrix.json: {e}",
                  file=sys.stderr, flush=True)

    label = ""
    if best is None:
        # device unusable: measure the same program on CPU so the record
        # is a clearly-labelled fallback number, not a crash log. Wider
        # batches amortize the lockstep per-step cost, so try 64 lanes
        # first and keep the tiny shape as the last resort.
        print("device bench failed entirely; falling back to CPU",
              file=sys.stderr, flush=True)
        fallbacks = ((64, 3), (8, 2))
        for i, (b, d) in enumerate(fallbacks):
            remaining = total_budget - (time.monotonic() - t_start)
            # keep a reserve so the last-resort tiny stage always gets a
            # real slice of budget even if the wide stage times out
            reserve = 180.0 * (len(fallbacks) - 1 - i)
            best = run_stage(
                b, d, BUDGET,
                max(60.0, min(stage_timeout * 2, remaining - reserve)),
                force_cpu=True,
            )
            if best is not None:
                break
        label = " [CPU FALLBACK — device unusable]"

    if best is None:
        print(json.dumps({
            "metric": "batched alpha-beta+NNUE nodes/sec/chip [ALL STAGES FAILED]",
            "value": 0, "unit": "nodes/sec", "vs_baseline": 0.0,
        }))
        return

    cores = os.cpu_count() or 1
    baseline = 400_000 * cores  # reference NPS prior × host cores
    headline = {
        "metric": (
            f"batched alpha-beta+NNUE nodes/sec/chip "
            f"(B={best['B']}, depth={best['depth']}, "
            f"platform={best['platform']}, "
            f"row_mode={best.get('row_mode', 'scatter')}){label}"
        ),
        "value": round(best["nps"]),
        "unit": "nodes/sec",
        "vs_baseline": round(best["nps"] / baseline, 4),
    }
    # perf ledger (obs/perf.py, docs/perf.md): every RESULT row of this
    # run becomes ledger history, and the next BENCH_rNN.json artifact
    # is emitted from the ledger — build-info + env fingerprint attached
    results = {"headline": {"value": headline["value"],
                            "vs_baseline": headline["vs_baseline"]},
               "ramp_best": best}
    results.update({k: v for k, v in matrix.items() if v is not None})
    _ledger_record(results, source="bench", emit=True)
    print(json.dumps(headline))


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--stage":
        if os.environ.get("BENCH_FORCE_CPU"):
            from tools import force_cpu  # noqa: F401  (deregisters axon)
        stage_main(
            int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]),
            *(sys.argv[5:7] or ()),
        )
    elif len(sys.argv) >= 2 and sys.argv[1] == "--mesh-scaling-stage":
        mesh_scaling_child(int(sys.argv[2]))
    elif os.environ.get("BENCH_GATE") == "1":
        gate_main()
    else:
        main()
