"""Benchmark: batched alpha-beta + NNUE nodes/sec on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The north-star metric (BASELINE.md) is nodes/sec/chip on a 256-position
batch. vs_baseline divides by the reference client's own per-core NPS
scheduling prior (400 knps, reference: src/stats.rs:203-214) × host cores —
the documented proxy for "Stockfish-AVX2 on the same host" since this image
bundles no Stockfish binary to measure directly.

The search dispatches in bounded segments (ops/search.py
search_batch_resumable) so no single device program runs unboundedly; a
transient device/tunnel error is retried, then the batch shrinks.
"""
from __future__ import annotations

import json
import os
import sys
import time


def run_once(B: int, depth: int, budget: int):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fishnet_tpu.utils import enable_compile_cache

    enable_compile_cache()

    from fishnet_tpu.chess import Position
    from fishnet_tpu.models import nnue
    from fishnet_tpu.ops.board import from_position, stack_boards
    from fishnet_tpu.ops.search import search_batch_resumable

    # a spread of real game positions (openings → endgames)
    fens = [
        "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
        "r1bqkbnr/pppp1ppp/2n5/4p3/2B1P3/5N2/PPPP1PPP/RNBQK2R b KQkq - 3 3",
        "r3k2r/p1ppqpb1/bn2pnp1/3PN3/1p2P3/2N2Q1p/PPPBBPPP/R3K2R w KQkq - 0 1",
        "rnbq1k1r/pp1Pbppp/2p5/8/2B5/8/PPP1NnPP/RNBQK2R w KQ - 1 8",
        "r4rk1/1pp1qppp/p1np1n2/2b1p1B1/2B1P1b1/P1NP1N2/1PP1QPPP/R4RK1 w - - 0 10",
        "8/2p5/3p4/KP5r/1R3p1k/8/4P1P1/8 w - - 0 1",
        "4k3/8/8/8/8/8/4P3/4K3 w - - 0 1",
        "6k1/5ppp/8/8/8/8/5PPP/3R2K1 w - - 0 1",
    ]
    positions = [Position.from_fen(f) for f in fens]
    lanes = [from_position(positions[i % len(positions)]) for i in range(B)]
    roots = stack_boards(lanes)
    params = nnue.init_params(jax.random.PRNGKey(0), l1=64, feature_set="board768")

    max_ply = depth + 1
    depth_arr = jnp.full((B,), depth, jnp.int32)
    budget_arr = jnp.full((B,), budget, jnp.int32)

    # optional shared transposition table (BENCH_TT_LOG2=21 etc.); off by
    # default so the metric stays a raw search-throughput number
    tt = None
    tt_log2 = int(os.environ.get("BENCH_TT_LOG2", "0"))
    if tt_log2:
        from fishnet_tpu.ops import tt as tt_mod

        tt = tt_mod.make_table(tt_log2)

    # warmup / compile
    out = search_batch_resumable(
        params, roots, depth_arr, budget_arr, max_ply=max_ply, tt=tt
    )
    tt = out.pop("tt")
    jax.block_until_ready(out["nodes"])

    t0 = time.perf_counter()
    out = search_batch_resumable(
        params, roots, depth_arr, budget_arr, max_ply=max_ply, tt=tt
    )
    out.pop("tt")
    jax.block_until_ready(out["nodes"])
    dt = time.perf_counter() - t0

    total_nodes = int(np.asarray(out["nodes"]).sum())
    return total_nodes / dt


def main() -> None:
    B = int(os.environ.get("BENCH_LANES", "256"))
    DEPTH = int(os.environ.get("BENCH_DEPTH", "4"))
    BUDGET = int(os.environ.get("BENCH_BUDGET", "200000"))

    # ramp up through configs so a crash at the big shape still leaves the
    # largest WORKING number on record (r1 recorded nothing because all
    # attempts used the big shape). Each stage retries once.
    stages = [(8, 2), (64, 3), (B, DEPTH)]
    best = None  # (nps, b, d)
    last_err = None
    for b, d in stages:
        ok = False
        for attempt in range(2):
            try:
                t0 = time.perf_counter()
                nps = run_once(b, d, BUDGET)
                dt = time.perf_counter() - t0
                print(f"bench stage B={b} depth={d}: {nps:,.0f} nodes/s "
                      f"({dt:.1f}s incl. warmup)", file=sys.stderr)
                best = (nps, b, d)
                ok = True
                break
            except Exception as e:
                last_err = e
                print(f"bench stage (B={b}, depth={d}) attempt {attempt} "
                      f"failed: {e}", file=sys.stderr)
                time.sleep(10.0)
        if not ok:
            break  # don't push a crashing device harder

    label = ""
    if best is None:
        # device unusable: measure the same program on CPU so the record
        # is a clearly-labelled fallback number, not a crash log
        print(f"device bench failed entirely ({last_err}); "
              "falling back to CPU", file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            import jax
            import jax._src.xla_bridge as _xb

            _xb._backend_factories.pop("axon", None)
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        nps = run_once(16, 2, BUDGET)
        best = (nps, 16, 2)
        label = " [CPU FALLBACK — device crashed]"

    nps, b, d = best
    cores = os.cpu_count() or 1
    baseline = 400_000 * cores  # reference NPS prior × host cores
    print(
        json.dumps(
            {
                "metric": (
                    f"batched alpha-beta+NNUE nodes/sec/chip "
                    f"(B={b}, depth={d}){label}"
                ),
                "value": round(nps),
                "unit": "nodes/sec",
                "vs_baseline": round(nps / baseline, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
