#!/bin/sh
# Env-var → flag mapping (reference: docker-entrypoint.sh:1-16).
set -e

ARGS="run --no-conf"
[ -n "$KEY" ] && ARGS="$ARGS --key $KEY"
[ -n "$KEY_FILE" ] && ARGS="$ARGS --key-file $KEY_FILE"
[ -n "$CORES" ] && ARGS="$ARGS --cores $CORES"
[ -n "$ENDPOINT" ] && ARGS="$ARGS --endpoint $ENDPOINT"
[ -n "$BACKEND" ] && ARGS="$ARGS --backend $BACKEND"
[ -n "$TPU_WEIGHTS" ] && ARGS="$ARGS --tpu-weights $TPU_WEIGHTS"
[ -n "$USER_BACKLOG" ] && ARGS="$ARGS --user-backlog $USER_BACKLOG"
[ -n "$SYSTEM_BACKLOG" ] && ARGS="$ARGS --system-backlog $SYSTEM_BACKLOG"
[ -n "$MAX_BACKOFF" ] && ARGS="$ARGS --max-backoff $MAX_BACKOFF"
[ -n "$CPU_PRIORITY" ] && ARGS="$ARGS --cpu-priority $CPU_PRIORITY"
[ -n "$STATS_FILE" ] && ARGS="$ARGS --stats-file $STATS_FILE"
[ -n "$NO_STATS_FILE" ] && ARGS="$ARGS --no-stats-file"

exec python -m fishnet_tpu $ARGS "$@"
